"""ComponentConfig (config/) + metrics registry (metrics/).

Mirrors the consumed subset of apis/config/types.go:37 and
metrics/metrics.go:196-460: config round-trip + validation, profile
construction from config (enable/disable/weights/strategy), and the
scheduler's series moving during real scheduling.
"""

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.config import (KubeSchedulerConfiguration,
                                   KubeSchedulerProfile, PluginSet,
                                   build_profiles)
from kubernetes_tpu.metrics import (Counter, Gauge, Histogram, Registry,
                                    SchedulerMetrics)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class TestConfig:
    def test_round_trip(self):
        cfg = KubeSchedulerConfiguration(
            profiles=[
                KubeSchedulerProfile(scheduler_name="default-scheduler"),
                KubeSchedulerProfile(
                    scheduler_name="batch",
                    plugins=PluginSet(disabled=["InterPodAffinity"]),
                    plugin_weights={"TaintToleration": 5},
                    scoring_strategy="MostAllocated"),
            ],
            pod_initial_backoff_seconds=0.5,
            pod_max_backoff_seconds=5.0,
            batch_size=1024)
        cfg.validate()
        again = KubeSchedulerConfiguration.from_dict(cfg.to_dict())
        assert again == cfg

    def test_compilation_cache_knob(self, tmp_path):
        """ISSUE 3 satellite: the persistent XLA compilation cache knob
        round-trips, applies to jax config once, and 'off' disables."""
        import jax

        from kubernetes_tpu import config as config_mod

        cfg = KubeSchedulerConfiguration(
            compilation_cache_dir=str(tmp_path / "xla"))
        cfg.validate()
        again = KubeSchedulerConfiguration.from_dict(cfg.to_dict())
        assert again.compilation_cache_dir == cfg.compilation_cache_dir
        # default present in the dict form
        assert (KubeSchedulerConfiguration().to_dict()["compilationCacheDir"]
                == "~/.cache/ktpu-xla")
        prev_applied = config_mod._cc_applied
        prev_dir = jax.config.jax_compilation_cache_dir
        try:
            config_mod._cc_applied = False
            assert config_mod.apply_compilation_cache("off") is False
            assert config_mod.apply_compilation_cache(
                str(tmp_path / "xla")) is True
            assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
            # once-guard: a second call is a no-op (returns True, no rewrite)
            assert config_mod.apply_compilation_cache("/nope") is True
            assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
        finally:
            config_mod._cc_applied = prev_applied
            jax.config.update("jax_compilation_cache_dir", prev_dir)

    def test_profiler_trace_dir_knob(self, tmp_path):
        cfg = KubeSchedulerConfiguration(
            profiler_trace_dir=str(tmp_path / "prof"))
        cfg.validate()
        again = KubeSchedulerConfiguration.from_dict(cfg.to_dict())
        assert again.profiler_trace_dir == cfg.profiler_trace_dir
        assert KubeSchedulerConfiguration().to_dict()[
            "profilerTraceDir"] == ""
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        assert sched.profiler_trace_dir == cfg.profiler_trace_dir

    def test_yaml_load(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("""
profiles:
- schedulerName: default-scheduler
  pluginWeights: {NodeAffinity: 7}
batchSize: 256
podInitialBackoffSeconds: 2
podMaxBackoffSeconds: 20
""")
        from kubernetes_tpu.config import load
        cfg = load(str(p))
        assert cfg.batch_size == 256
        assert cfg.profiles[0].plugin_weights == {"NodeAffinity": 7}

    def test_validation_rejects(self):
        with pytest.raises(ValueError, match="duplicate"):
            KubeSchedulerConfiguration(profiles=[
                KubeSchedulerProfile(), KubeSchedulerProfile()]).validate()
        with pytest.raises(ValueError, match="unknown plugin"):
            KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
                plugins=PluginSet(disabled=["NoSuchPlugin"]))]).validate()
        with pytest.raises(ValueError, match="podMaxBackoff"):
            KubeSchedulerConfiguration(
                pod_initial_backoff_seconds=5,
                pod_max_backoff_seconds=1).validate()
        with pytest.raises(ValueError, match="scoringStrategy"):
            KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
                scoring_strategy="Weird")]).validate()

    def test_build_profiles_disable_and_weights(self):
        cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
            plugins=PluginSet(disabled=["InterPodAffinity",
                                        "PodTopologySpread"]),
            plugin_weights={"NodeAffinity": 9})])
        (prof,) = build_profiles(cfg)
        names = {p.name() for p in prof.framework.plugins}
        assert "InterPodAffinity" not in names
        assert "PodTopologySpread" not in names
        assert prof.framework.weights["NodeAffinity"] == 9
        assert prof.score_config.w_node_affinity == 9

    def test_scheduler_consumes_config(self):
        cfg = KubeSchedulerConfiguration(
            batch_size=128, pod_initial_backoff_seconds=2.0,
            pod_max_backoff_seconds=30.0)
        api = APIServer()
        sched = Scheduler(api, config=cfg)
        assert sched.batch_size == 128
        assert sched.queue.pod_initial_backoff == 2.0
        assert sched.queue.pod_max_backoff == 30.0
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        api.create_pod(make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1

    def test_most_allocated_strategy_routes_to_scan(self):
        """MostAllocated packs onto the fewest nodes (the closed form is
        gated off; decisions still match the host oracle's strategy)."""
        cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
            scoring_strategy="MostAllocated")])
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        for i in range(3):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 100}).obj())
        for i in range(6):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 6
        used = {p.spec.node_name for p in api.pods.values()}
        assert len(used) == 1  # bin-packing: all on one node


class TestMetricsPrimitives:
    def test_counter_labels(self):
        c = Counter("x_total", "help", ("a",))
        c.inc("one")
        c.inc("one")
        c.inc("two", by=3)
        assert c.value("one") == 2 and c.value("two") == 3
        text = "\n".join(c.expose())
        assert 'x_total{a="one"} 2' in text

    def test_histogram_buckets(self):
        h = Histogram("lat", "help", buckets=[0.1, 1.0])
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        assert h.count() == 3 and h.sum() == 5.55
        text = "\n".join(h.expose())
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="1"} 2' in text
        assert 'lat_bucket{le="+Inf"} 3' in text

    def test_registry_rejects_duplicates(self):
        r = Registry()
        r.register(Counter("dup", "h"))
        with pytest.raises(ValueError):
            r.register(Gauge("dup", "h"))

    def test_label_value_escaping(self):
        """Text-format spec: backslash, quote and newline in label values
        must be escaped (they used to be emitted raw)."""
        c = Counter("esc_total", "h", ("msg",))
        c.inc('say "hi"\nback\\slash')
        line = [ln for ln in c.expose() if not ln.startswith("#")][0]
        assert line == ('esc_total{msg="say \\"hi\\"\\nback\\\\slash"} 1')

    def test_help_escaping(self):
        c = Counter("h_total", "line1\nline2 with \\ backslash")
        help_line = c.expose()[0]
        assert help_line == ("# HELP h_total line1\\nline2 with "
                             "\\\\ backslash")
        assert "\n" not in help_line

    def test_histogram_quantile(self):
        h = Histogram("q", "h", buckets=[0.001, 0.01, 0.1, 1.0])
        for _ in range(90):
            h.observe(0.005, "a")     # second bucket
        for _ in range(10):
            h.observe(0.5, "b")       # fourth bucket (labels merge)
        assert 0.001 <= h.quantile(0.5) <= 0.01
        assert 0.1 <= h.quantile(0.99) <= 1.0
        assert Histogram("empty", "h").quantile(0.5) == 0.0


def _parse_exposition(text: str):
    """Minimal promtool-style parse: returns (series, helps, types) where
    series maps sample name → list of (labels dict, value)."""
    import re
    series: dict = {}
    helps: dict = {}
    types: dict = {}
    lbl_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, h = line[len("# HELP "):].partition(" ")
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = h
            continue
        if line.startswith("# TYPE "):
            name, _, t = line[len("# TYPE "):].partition(" ")
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = t
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$", line)
        assert m, f"unparseable sample line: {line!r}"
        name, labels_raw, value = m.groups()
        labels = dict(lbl_re.findall(labels_raw or ""))
        for v in labels.values():
            assert "\n" not in v
        series.setdefault(name, []).append((labels, float(value)))
    return series, helps, types


class TestExpositionLint:
    """promtool-style lint over a fully-seeded exposition: every series
    has HELP+TYPE, no duplicates, histogram buckets cumulative and capped
    by +Inf, label values escaped."""

    def test_fully_seeded_exposition_lints_clean(self):
        m = SchedulerMetrics()
        # drive a nasty label value through a real series to prove the
        # parse survives escaping end to end
        m.api_retries.inc('bind "quoted"\nvalue')
        text = m.exposition()
        series, helps, types = _parse_exposition(text)

        base = {n[:-len(suffix)] if n.endswith(suffix) else n
                for n in series
                for suffix in ("_bucket", "_sum", "_count")
                if n.endswith(suffix) or suffix == "_count"}
        # every emitted sample belongs to a declared metric family
        for name in series:
            root = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[:-len(suffix)] in types:
                    root = name[:-len(suffix)]
                    break
            assert root in types, f"sample {name} without TYPE"
            assert root in helps, f"sample {name} without HELP"
            assert base is not None

        # every REGISTERED metric is pre-seeded: at least one sample per
        # family (the satellite requirement — dashboards always see the
        # series)
        for name, t in types.items():
            if t == "histogram":
                assert f"{name}_count" in series, f"{name} unseeded"
                assert f"{name}_sum" in series
            elif name == "scheduler_pending_pods":
                continue   # callback gauge: no callback wired here
            else:
                assert name in series, f"{name} unseeded"

        # histogram buckets: per label set, cumulative and +Inf-capped
        for name, t in types.items():
            if t != "histogram":
                continue
            by_key: dict = {}
            for labels, value in series.get(f"{name}_bucket", []):
                le = labels.pop("le")
                key = tuple(sorted(labels.items()))
                by_key.setdefault(key, []).append((le, value))
            counts = {tuple(sorted(lbl.items())): v
                      for lbl, v in series.get(f"{name}_count", [])}
            for key, buckets in by_key.items():
                les = [le for le, _ in buckets]
                assert les.count("+Inf") == 1, f"{name}{key} missing +Inf"
                assert les[-1] == "+Inf", f"{name}{key} +Inf not last"
                values = [v for _, v in buckets]
                assert values == sorted(values), \
                    f"{name}{key} buckets not cumulative"
                assert values[-1] == counts[key]

    def test_no_duplicate_series_names(self):
        m = SchedulerMetrics()
        seen = set()
        for metric in m.registry._metrics.values():
            assert metric.name not in seen
            seen.add(metric.name)

    def test_issue10_families_covered_by_lint(self):
        """ISSUE 10 satellite: the audit/explain/SLO families are
        registered AND pre-seeded, so the generic lint above (HELP+TYPE,
        escaping, +Inf caps) actually exercises them — plus the exact
        label sets dashboards key on."""
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_oracle_divergence_total"] == "counter"
        assert types["scheduler_shadow_audit_drains_total"] == "counter"
        assert types["scheduler_slo_burn_rate"] == "gauge"
        assert types["scheduler_audit_replay_seconds"] == "histogram"
        assert types["scheduler_explain_seconds"] == "histogram"
        kinds = {lbl["kind"] for lbl, _v in
                 series["scheduler_oracle_divergence_total"]}
        assert kinds == {"assignment", "reason", "verdict"}
        outcomes = {lbl["outcome"] for lbl, _v in
                    series["scheduler_shadow_audit_drains_total"]}
        assert outcomes == {"clean", "divergent", "skipped", "error"}
        burn = {(lbl["sli"], lbl["window"]) for lbl, _v in
                series["scheduler_slo_burn_rate"]}
        from kubernetes_tpu.obs.slo import DEFAULT_OBJECTIVES, WINDOWS
        assert burn == {(sli, w) for sli in DEFAULT_OBJECTIVES
                        for _s, w in WINDOWS}
        # histogram families carry the +Inf cap via the generic lint;
        # assert their zero-seed is present too
        assert ("scheduler_audit_replay_seconds_count" in series
                and "scheduler_explain_seconds_count" in series)

    def test_issue13_families_covered_by_lint(self):
        """ISSUE 13 satellite: the journey/timeline/cluster-probe
        families are registered AND pre-seeded with the EXACT label sets
        the dashboards (and /debug surfaces) key on."""
        from kubernetes_tpu.metrics import (CLUSTER_DOM_STATS,
                                            CLUSTER_FRAG_KINDS,
                                            CLUSTER_SEED_RESOURCES,
                                            CLUSTER_UTIL_STATS)
        from kubernetes_tpu.obs.journey import CAUSES, EVENTS, SEGMENTS
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_e2e_segment_seconds"] == "histogram"
        assert types["scheduler_pod_requeues_total"] == "counter"
        assert types["scheduler_journey_transitions_total"] == "counter"
        assert types["scheduler_cluster_utilization_ratio"] == "gauge"
        assert types["scheduler_cluster_fragmentation_index"] == "gauge"
        assert types["scheduler_cluster_domain_imbalance"] == "gauge"
        # the e2e decomposition's exact segment set
        segments = {lbl["segment"] for lbl, _v in
                    series["scheduler_e2e_segment_seconds_count"]}
        assert segments == set(SEGMENTS)
        assert set(SEGMENTS) == {"queue_wait", "gate_wait", "drain",
                                 "commit_backlog"}
        # the requeue-cause label set (every chaos path maps to one)
        causes = {lbl["cause"] for lbl, _v in
                  series["scheduler_pod_requeues_total"]}
        assert causes == set(CAUSES)
        assert set(CAUSES) == {"preemption", "fence_unwind",
                               "breaker_fallback", "gang_split",
                               "resync", "bind_error", "unschedulable"}
        # every journey transition has a zero-seeded counter series
        events = {lbl["event"] for lbl, _v in
                  series["scheduler_journey_transitions_total"]}
        assert events == set(EVENTS)
        # cluster gauges: (resource, stat/kind) grid seeded for the
        # well-known resources; the probe resolve extends it live
        util = {(lbl["resource"], lbl["stat"]) for lbl, _v in
                series["scheduler_cluster_utilization_ratio"]}
        assert util >= {(r, s) for r in CLUSTER_SEED_RESOURCES
                        for s in CLUSTER_UTIL_STATS}
        frag = {(lbl["resource"], lbl["kind"]) for lbl, _v in
                series["scheduler_cluster_fragmentation_index"]}
        assert frag >= {(r, k) for r in CLUSTER_SEED_RESOURCES
                        for k in CLUSTER_FRAG_KINDS}
        dom = {lbl["stat"] for lbl, _v in
               series["scheduler_cluster_domain_imbalance"]}
        assert dom == set(CLUSTER_DOM_STATS)

    def test_issue14_families_covered_by_lint(self):
        """ISSUE 14 satellite: the kernel-observatory families are
        registered AND pre-seeded with the EXACT label sets — every
        ledger kernel on the kernel-labeled pair, one TPU host's worth
        of lanes on the shard gauge — so the generic lint exercises
        them before the first dispatch."""
        from kubernetes_tpu.metrics import SHARD_SEED_LANES
        from kubernetes_tpu.perf.ledger import KERNELS
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_kernel_device_seconds"] == "counter"
        assert types["scheduler_kernel_dispatch_total"] == "counter"
        assert types["scheduler_shard_lane_seconds"] == "gauge"
        assert types["scheduler_shard_imbalance_ratio"] == "gauge"
        for fam in ("scheduler_kernel_device_seconds",
                    "scheduler_kernel_dispatch_total"):
            kernels = {lbl["kernel"] for lbl, _v in series[fam]}
            assert kernels == set(KERNELS), fam
        lanes = {lbl["lane"] for lbl, _v in
                 series["scheduler_shard_lane_seconds"]}
        assert lanes == set(SHARD_SEED_LANES)
        assert set(SHARD_SEED_LANES) == {str(i) for i in range(8)}
        # the unlabeled imbalance gauge carries exactly one sample
        (lbl, val), = series["scheduler_shard_imbalance_ratio"]
        assert lbl == {} and val == 0.0

    def test_issue14_observatory_mirror_syncs_at_exposition(self):
        """The exposition mirrors the process-global observatory the
        same way it mirrors the compile ledger: absolute assignment of
        dispatch counts and warm seconds per kernel."""
        from kubernetes_tpu.perf.observatory import GLOBAL as obs
        obs.reset()
        try:
            obs.on_call("run_batch", 0.0, 0.050, False, ())
            obs.on_call("run_batch", 0.0, 0.030, False, ())
            obs.on_call("run_batch", 0.0, 2.000, True, ())  # compile
            obs.set_shard_profile({"laneSeconds": [0.5, 0.25],
                                   "imbalanceRatio": 1.33,
                                   "nDevices": 2})
            m = SchedulerMetrics()
            series, _h, _t = _parse_exposition(m.exposition())
            vals = {lbl["kernel"]: v for lbl, v in
                    series["scheduler_kernel_dispatch_total"]}
            assert vals["run_batch"] == 3.0
            secs = {lbl["kernel"]: v for lbl, v in
                    series["scheduler_kernel_device_seconds"]}
            # warm walls only: the compiling call's 2s stays out
            assert abs(secs["run_batch"] - 0.080) < 1e-9
            lanes = {lbl["lane"]: v for lbl, v in
                     series["scheduler_shard_lane_seconds"]}
            assert lanes["0"] == 0.5 and lanes["1"] == 0.25
            (_lbl, ratio), = series["scheduler_shard_imbalance_ratio"]
            assert abs(ratio - 1.33) < 1e-9
        finally:
            obs.reset()

    def test_issue17_families_covered_by_lint(self):
        """ISSUE 17 satellite: the sharded-control-plane families are
        registered AND pre-seeded with the EXACT label sets the shard
        dashboards (and bench_metrics.prom) key on."""
        from kubernetes_tpu.metrics import (CROSS_SHARD_OUTCOMES,
                                            SHARD_SEED_IDS,
                                            SHARD_STEAL_REASONS)
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_shard_assignments"] == "gauge"
        assert types["scheduler_shard_rebalance_seconds"] == "histogram"
        assert types["scheduler_shard_steals_total"] == "counter"
        assert types["scheduler_cross_shard_conflicts_total"] == "counter"
        shards = {lbl["shard"] for lbl, _v in
                  series["scheduler_shard_assignments"]}
        assert shards == set(SHARD_SEED_IDS)
        assert set(SHARD_SEED_IDS) == {str(i) for i in range(4)}
        reasons = {lbl["reason"] for lbl, _v in
                   series["scheduler_shard_steals_total"]}
        assert reasons == set(SHARD_STEAL_REASONS)
        assert set(SHARD_STEAL_REASONS) == {"split", "merge", "steal",
                                            "rebalance"}
        outcomes = {lbl["outcome"] for lbl, _v in
                    series["scheduler_cross_shard_conflicts_total"]}
        assert outcomes == set(CROSS_SHARD_OUTCOMES)
        assert set(CROSS_SHARD_OUTCOMES) == {"conflict", "fenced"}
        # the rebalance histogram's zero-seed rides the generic lint
        assert "scheduler_shard_rebalance_seconds_count" in series

    def test_issue18_families_covered_by_lint(self):
        """ISSUE 18 satellite: the streaming-pipeline families are
        registered AND pre-seeded with the EXACT stage label set the
        /debug/pipeline occupancy block and bench_metrics.prom key on —
        ingest | device | commit, nothing else, before the pipeline
        ever starts."""
        from kubernetes_tpu.pipeline import STAGES
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_pipeline_stage_busy_seconds"] == "counter"
        assert types["scheduler_pipeline_backpressure_total"] == "counter"
        for fam in ("scheduler_pipeline_stage_busy_seconds",
                    "scheduler_pipeline_backpressure_total"):
            stages = {lbl["stage"] for lbl, _v in series[fam]}
            assert stages == set(STAGES), fam
            # zero-seeded: every series present before the first drain
            assert all(v == 0.0 for _l, v in series[fam]), fam
        assert set(STAGES) == {"ingest", "device", "commit"}

    def test_issue19_families_covered_by_lint(self):
        """ISSUE 19 satellite: the incident-forensics counter is
        registered AND pre-seeded with the EXACT trigger label set the
        watchdog fires — dashboards can alert on rate() before the
        first capture."""
        from kubernetes_tpu.obs.incident import TRIGGERS
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_incidents_total"] == "counter"
        triggers = {lbl["trigger"] for lbl, _v in
                    series["scheduler_incidents_total"]}
        assert triggers == set(TRIGGERS)
        assert set(TRIGGERS) == {"slo_breach", "divergence",
                                 "fence_storm", "pipeline_stall"}
        assert all(v == 0.0
                   for _l, v in series["scheduler_incidents_total"])

    def test_issue20_families_covered_by_lint(self):
        """ISSUE 20 satellite: the critical-path families are registered
        AND pre-seeded with the EXACT cause taxonomy the verdicts emit
        and bench_metrics.prom keys on — dashboards can rate() both
        before the first drain commits."""
        from kubernetes_tpu.perf.critical_path import CAUSES
        m = SchedulerMetrics()
        series, helps, types = _parse_exposition(m.exposition())
        assert types["scheduler_critical_path_seconds"] == "counter"
        assert types["scheduler_bottleneck_drains_total"] == "counter"
        for fam in ("scheduler_critical_path_seconds",
                    "scheduler_bottleneck_drains_total"):
            causes = {lbl["cause"] for lbl, _v in series[fam]}
            assert causes == set(CAUSES), fam
            # zero-seeded: every cause series present before any verdict
            assert all(v == 0.0 for _l, v in series[fam]), fam
        assert set(CAUSES) == {"host_build", "device_compute",
                               "device_comms", "commit", "backpressure",
                               "idle"}


class TestSchedulerMetrics:
    def test_series_move_during_scheduling(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("big").req({"cpu": "64", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        m = sched.metrics
        assert m.schedule_attempts.value("scheduled", "default-scheduler") == 3
        assert m.schedule_attempts.value("unschedulable",
                                         "default-scheduler") == 1
        assert m.device_batch_size.count() >= 1
        assert m.sli_duration.count("1") == 3
        assert m.api_dispatcher_calls.value("pod_binding", "success") == 3
        depths = sched._queue_depths()
        assert depths[("unschedulable",)] == 1.0
        text = m.exposition()
        assert "scheduler_schedule_attempts_total" in text
        assert "scheduler_pending_pods" in text

    def test_disable_preemption_via_config(self):
        cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
            plugins=PluginSet(disabled=["DefaultPreemption"]))])
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        api.create_pod(make_pod("low").req(
            {"cpu": "4", "memory": "1Gi"}).priority(0).obj())
        assert sched.schedule_pending() == 1
        api.create_pod(make_pod("vip").req(
            {"cpu": "4", "memory": "1Gi"}).priority(100).obj())
        assert sched.schedule_pending() == 0
        # preemption off: no eviction, no nomination
        assert "default/low" in api.pods
        assert api.pods["default/vip"].status.nominated_node_name == ""
        assert sched.preemption_attempts == 0


class TestMultiProfile:
    def test_two_profiles_route_by_scheduler_name(self):
        """profile.go:46: a drain mixing schedulerNames must run each pod
        under ITS profile's strategy — spread pods via LeastAllocated,
        binpack pods via MostAllocated — on the device path."""
        cfg = KubeSchedulerConfiguration(profiles=[
            KubeSchedulerProfile(scheduler_name="default-scheduler"),
            KubeSchedulerProfile(scheduler_name="binpack",
                                 scoring_strategy="MostAllocated"),
        ])
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        for i in range(4):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 16, "memory": "32Gi", "pods": 100}).obj())
        for i in range(4):
            api.create_pod(make_pod(f"spread{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        for i in range(4):
            p = make_pod(f"pack{i}").req({"cpu": "1", "memory": "1Gi"}).obj()
            p.spec.scheduler_name = "binpack"
            api.create_pod(p)
        assert sched.schedule_pending() == 8
        spread_nodes = {api.pods[f"default/spread{i}"].spec.node_name
                        for i in range(4)}
        pack_nodes = {api.pods[f"default/pack{i}"].spec.node_name
                      for i in range(4)}
        assert len(spread_nodes) == 4   # LeastAllocated round-robins
        assert len(pack_nodes) == 1     # MostAllocated bin-packs

    def test_unowned_scheduler_name_is_dropped(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        p = make_pod("alien").req({"cpu": "1", "memory": "1Gi"}).obj()
        p.spec.scheduler_name = "someone-else"
        api.create_pod(p)
        assert sched.schedule_pending() == 0
        assert api.pods["default/alien"].spec.node_name == ""


class TestRegistry:
    def test_factories_build_fresh_instances(self):
        from kubernetes_tpu.config import default_registry
        reg = default_registry()
        a = reg.factories["GangScheduling"]()
        b = reg.factories["GangScheduling"]()
        assert a is not b

    def test_enabled_without_factory_raises(self):
        cfg = KubeSchedulerConfiguration(
            extra_plugins=("MyPlugin",),
            profiles=[KubeSchedulerProfile(
                plugins=PluginSet(enabled=["MyPlugin"]))])
        cfg.validate()   # name is vouched for...
        with pytest.raises(ValueError, match="no registered factory"):
            build_profiles(cfg)  # ...but no factory: must not run without it

    def test_extra_plugins_round_trip(self):
        cfg = KubeSchedulerConfiguration(extra_plugins=("MyPlugin",))
        again = KubeSchedulerConfiguration.from_dict(cfg.to_dict())
        assert again.extra_plugins == ("MyPlugin",)
        again.validate()


class TestFeatureGates:
    """config/features.py: featuregate registry + scheduler consultation
    (kube_features.go:686 OpportunisticBatching, :891 AsyncAPICalls)."""

    def test_defaults_and_overrides(self):
        from kubernetes_tpu.config.features import default_gate
        g = default_gate()
        assert g.enabled("OpportunisticBatching")
        g.set("OpportunisticBatching", False)
        assert not g.enabled("OpportunisticBatching")

    def test_unknown_gate_rejected(self):
        from kubernetes_tpu.config.features import default_gate
        with pytest.raises(ValueError, match="unknown feature gate"):
            default_gate({"NoSuchGate": True})
        cfg = KubeSchedulerConfiguration(feature_gates={"Bogus": True})
        with pytest.raises(ValueError):
            cfg.validate()

    def test_gate_flips_uniform_fast_path(self, monkeypatch):
        """With OpportunisticBatching off, run_uniform must never be
        invoked — every drain takes the scan program."""
        import kubernetes_tpu.scheduler as sched_mod

        def boom(*a, **k):
            raise AssertionError("run_uniform called with gate off")

        api = APIServer()
        cfg = KubeSchedulerConfiguration(
            feature_gates={"OpportunisticBatching": False})
        sched = Scheduler(api, batch_size=64, config=cfg)
        monkeypatch.setattr(sched_mod, "run_uniform", boom)
        for i in range(3):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
        for i in range(40):   # >= UNIFORM_RUN_MIN, would trigger top-L
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj())
        assert sched.schedule_pending() == 40

    def test_async_api_calls_gate_sets_pipeline_depth(self):
        api = APIServer()
        cfg = KubeSchedulerConfiguration(
            feature_gates={"SchedulerAsyncAPICalls": False})
        sched = Scheduler(api, config=cfg)
        assert sched.max_inflight_drains == 0
        assert Scheduler(APIServer()).max_inflight_drains == 8


class TestPluginArgs:
    """Typed per-plugin args (types_pluginargs.go analog)."""

    def test_most_allocated_via_plugin_args_packs(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [{
            "pluginArgs": {"NodeResourcesFit": {
                "scoringStrategy": "MostAllocated"}},
        }]})
        cfg.validate()
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        assert (next(iter(sched.profiles.values()))
                .score_config.strategy == "MostAllocated")
        for i in range(2):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 110}).obj())
        # pre-load n1; MostAllocated must PACK subsequent pods onto it
        seed = make_pod("seed").req({"cpu": "2", "memory": "2Gi"}).obj()
        api.create_pod(seed)
        api.bind(seed, "n1")
        for i in range(3):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 3
        assert all(api.pods[f"default/p{i}"].spec.node_name == "n1"
                   for i in range(3))

    def test_unknown_arg_field_rejected(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [{
            "pluginArgs": {"NodeResourcesFit": {"scoringStratgy": "x"}}}]})
        with pytest.raises(ValueError, match="unknown NodeResourcesFitArgs"):
            cfg.validate()

    def test_args_for_unknown_plugin_rejected(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [{
            "pluginArgs": {"NoSuchPlugin": {}}}]})
        with pytest.raises(ValueError, match="unknown plugin"):
            cfg.validate()

    def test_gang_timeout_arg_applied(self):
        from kubernetes_tpu.config import build_profiles
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [{
            "pluginArgs": {"GangScheduling": {
                "schedulingTimeoutSeconds": 42}}}]})
        cfg.validate()
        profs = build_profiles(cfg, APIServer())
        gang = next(p for p in profs[0].framework.plugins
                    if p.name() == "GangScheduling")
        assert gang.scheduling_timeout_seconds == 42


class TestObservability:
    """Leveled logging + sampled plugin metrics + cache comparer
    (metrics.go:322, debugger.go:31-76)."""

    def _tiny_cluster(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=16)
        api.create_node(make_node("n0").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
        return api, sched

    def test_plugin_execution_duration_sampled_on_host_path(self):
        api, sched = self._tiny_cluster()
        sched.UNIFORM_RUN_MIN = 10**9
        # host path via extenders-free... force host: use schedule_one
        for i in range(12):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj())
        for _ in range(12):
            sched.schedule_one()
        hist = sched.metrics.plugin_execution_duration
        # ~10% sampling over 12 attempts -> at least one Filter sample
        assert hist.count("NodeResourcesFit", "Filter", "SUCCESS") >= 1

    def test_plugin_evaluation_total_counts_device_batches(self):
        api, sched = self._tiny_cluster()
        for i in range(8):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj())
        assert sched.schedule_pending() == 8
        assert sched.metrics.plugin_evaluation_total.value(
            "NodeResourcesFit", "Filter", "default-scheduler") == 8

    def test_cache_comparer_clean_and_divergent(self):
        api, sched = self._tiny_cluster()
        api.create_pod(make_pod("p0").req(
            {"cpu": "100m", "memory": "64Mi"}).obj())
        assert sched.schedule_pending() == 1
        assert sched.debugger.compare() == []
        # inject divergence: drop the pod from the cache behind the
        # scheduler's back
        sched.cache.pod_states.pop("default/p0")
        sched.cache.assumed_pods.discard("default/p0")
        problems = sched.debugger.compare()
        assert any("not in cache" in p for p in problems)
        assert sched.metrics.cache_divergence.value("host_vs_apiserver") >= 1

    def test_debug_compare_both_layers(self):
        api, sched = self._tiny_cluster()
        api.create_pod(make_pod("p0").req(
            {"cpu": "100m", "memory": "64Mi"}).obj())
        sched.schedule_pending()
        out = sched.debug_compare()
        assert out == {"device_vs_host": [], "host_vs_apiserver": []}

    def test_klog_levels(self, capsys):
        from kubernetes_tpu.utils.logging import klog, set_verbosity, verbosity
        old = verbosity()
        try:
            set_verbosity(2)
            assert klog.v(2).enabled and not klog.v(5).enabled
            set_verbosity(5)
            assert klog.v(5).enabled
        finally:
            set_verbosity(old)
