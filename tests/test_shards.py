"""Sharded control plane (ISSUE 17): N fenced schedulers over one cluster.

The gates this file establishes:

- the ShardMap is a fenced, versioned CAS object: topology changes lose
  version races (`Conflict`) and stale-fenced writers (`FencedWrite`);
  routing falls back to a process-independent hash for unmapped keys;
- split (1→N): each shard's slice is scheduled ONLY by its lease holder,
  peers keep the slice warm PARKED (watch-fed, never queued), and the
  fleet's final assignment map byte-matches a single-scheduler replay
  twin driven by the recorded commit order;
- steal mid-drain: a victim holding an uncommitted flush is fenced by
  the generation bump — every late bind is rejected server-side, the
  assumes unwind, the successor binds each pod exactly once;
- merge (N→1): ownership collapses onto one instance with the
  predecessors' audit-chain positions annexed (`record_handoff`), and
  every per-shard ledger verifies across every handoff;
- the kill-at-every-phase matrix (slow): a shard leader dies at
  host_build / device / commit / mid-flush, a peer steals the orphaned
  shard, and the outcome is indistinguishable from a serial run — zero
  double-binds (`binding_count` exact), zero oracle divergence at 100%
  sampling, replay-twin parity;
- seeded lease storms (chaos): expiry/steal strikes aimed at the shard
  leases shake ownership repeatedly; the fleet still converges with
  zero double-binds and intact ledgers.

Plus the satellite regressions: the standby sync-vs-watch ingest race
(ISSUE 17 bugfix), shard-aware chaos targeting, the cross-shard
conflict fuzz, /debug/shards, and the flight-record shard tag.
"""

import os
import random
import threading

import pytest

from kubernetes_tpu.backend.apiserver import (APIServer, Conflict,
                                              FencedWrite, ShardMap)
from kubernetes_tpu.ha import (LeaderElector, ShardManager, ShardScheduler,
                               StandbyScheduler, fence_dispatcher,
                               shard_key, shard_lease_name)
from kubernetes_tpu.obs.audit import DrainLedger
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.chaos import ChaosAPIServer, ChaosConfig
from kubernetes_tpu.testing.wrappers import make_node, make_pod

SEED = int(os.environ.get("CHAOS_SEED", "7"))


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class Killed(Exception):
    """Simulated process death: propagates out of the scheduling loop,
    leaving whatever the 'process' had not committed uncommitted."""


def _no_sleep(sched):
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _audited(sched):
    assert sched.audit is not None, "ShadowOracleAudit gate must be on"
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    return sched


def _nodes(api, n=6, cpu=16, mem="32Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _specs(n, seed, prefix="p", ns="default"):
    rng = random.Random(seed)
    return [(f"{prefix}{i}", ns, 250 * rng.randint(1, 6),
             512 * rng.randint(1, 4)) for i in range(n)]


def _create(api, specs, raw=None):
    """Create the pods; `raw` (uid → spec tuple) feeds the replay twin."""
    for name, ns, cpu, mem in specs:
        pod = make_pod(name, namespace=ns).req(
            {"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj()
        if raw is not None:
            raw[pod.uid] = (name, ns, cpu, mem)
        api.create_pod(pod)


def _assignments(api):
    return {uid: p.spec.node_name for uid, p in api.pods.items()}


def _shard(client, identity, clock, **kw):
    inst = ShardScheduler(client, identity=identity, clock=clock,
                          batch_size=32, **kw)
    _audited(_no_sleep(inst.scheduler))
    return inst


def _drive(api, insts, clock, want_bound, mgr=None, max_rounds=80):
    """Round-robin the fleet to quiescence: tick (elections), drain,
    advance time, retry backoffs — the fleet's control loop."""
    for _ in range(max_rounds):
        for inst in insts:
            inst.tick()
            inst.scheduler.schedule_pending()
            clock.t += 5.0
            inst.scheduler.flush_queues()
        if mgr is not None:
            mgr.sync_all()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= want_bound:
            return
    bound = sum(1 for p in api.pods.values() if p.spec.node_name)
    raise AssertionError(f"fleet did not quiesce: {bound}/{want_bound}")


class BindRecorder:
    """Wraps the store's bind verbs to record every committed chunk
    (uid, node) in commit order — the replay-twin's script. Installed on
    the INNER store so chaos/killer facades route through it."""

    def __init__(self, api):
        self.chunks = []
        self._real_all, self._real_one = api.bind_all, api.bind
        api.bind_all = self._bind_all
        api.bind = self._bind

    def _bind_all(self, pairs, fence_token=None):
        failures = self._real_all(pairs, fence_token=fence_token)
        failed = {p.uid for p, _e in failures}
        chunk = [(a.uid, a.spec.node_name) for a, _o in pairs
                 if a.uid not in failed]
        if chunk:
            self.chunks.append(chunk)
        return failures

    def _bind(self, pod, node_name, fence_token=None):
        out = self._real_one(pod, node_name, fence_token=fence_token)
        self.chunks.append([(pod.uid, node_name)])
        return out


def _replay_twin(raw, chunks, n_nodes, cpu=32, mem="64Gi"):
    """Feed the recorded commit order, chunk by chunk, to ONE fresh
    scheduler on a fresh store: if sharding changed nothing but WHO
    drains a pod, the twin's final assignment map is byte-identical."""
    api = APIServer()
    _nodes(api, n=n_nodes, cpu=cpu, mem=mem)
    clock = Clock()
    sched = _audited(_no_sleep(Scheduler(api, batch_size=32, clock=clock)))
    want = 0
    for chunk in chunks:
        _create(api, [raw[uid] for uid, _node in chunk])
        want += len(chunk)
        for _ in range(60):
            sched.schedule_pending()
            if sum(1 for p in api.pods.values() if p.spec.node_name) >= want:
                break
            clock.t += 5.0
            sched.flush_queues()
    assert sched.reconcile() == []
    return _assignments(api)


def _fleet(api, clock, identities=("sched-a", "sched-b"), clients=None):
    insts = [_shard((clients or {}).get(ident, api), ident, clock)
             for ident in identities]
    mgr = ShardManager(api, instances=insts, clock=clock)
    mgr.wire_ledgers()
    return insts, mgr


# -- the ShardMap object -------------------------------------------------------


def test_shard_map_cas_fencing_and_routing():
    """The shard map is itself a fenced, versioned API object: CAS races
    lose with Conflict, stale fences with FencedWrite; routing prefers
    the explicit assignment and falls back to a stable hash."""
    api = APIServer()
    m = api.get_shard_map()
    assert m.num_shards == 1 and m.version == 0    # absent = trivial map

    out = api.put_shard_map(ShardMap(num_shards=4, assignments={
        "default-scheduler/team-a": 0}), expect_version=0)
    assert out.version == 1 and out.num_shards == 4
    # version race: the CAS loser is told, not silently overwritten
    with pytest.raises(Conflict):
        api.put_shard_map(ShardMap(num_shards=2), expect_version=0)
    # explicit assignment wins; unmapped keys hash deterministically
    assert out.shard_for("default-scheduler/team-a") == 0
    sid = out.shard_for("default-scheduler/team-z")
    assert 0 <= sid < 4
    assert sid == out.shard_for("default-scheduler/team-z")    # stable
    # an out-of-range assignment (map shrank) falls back to the hash
    stale = api.put_shard_map(ShardMap(num_shards=2, assignments={
        "default-scheduler/team-a": 3}), expect_version=1)
    assert 0 <= stale.shard_for("default-scheduler/team-a") < 2

    # topology writes are fenced like any other write
    api.acquire_lease(shard_lease_name(0), "sched-a", 0.0)
    with pytest.raises(FencedWrite):
        api.put_shard_map(ShardMap(num_shards=8), expect_version=2,
                          fence_token=(shard_lease_name(0), 99))
    api.put_shard_map(ShardMap(num_shards=8), expect_version=2,
                      fence_token=(shard_lease_name(0), 1))


def test_ledger_handoff_annex():
    """The handoff annex is its own hash chain: entries fold from
    genesis, verify_handoffs replays the fold, tampering breaks it."""
    led = DrainLedger()
    e1 = led.record_handoff(0, "abcd" * 16, 7)
    e2 = led.record_handoff(1, "beef" * 16, 12)
    assert e2["prev"] == e1["hash"]
    assert led.verify_handoffs()
    assert led.verify()                      # the drain chain is untouched
    led.handoffs[0]["seq"] = 99              # tamper
    assert not led.verify_handoffs()


# -- split: fenced slices, warm parks, twin parity -----------------------------


def test_split_two_shards_twin_parity():
    """1→2 split: each namespace's slice binds under its own shard
    lease, peers park (never queue) the other slice, and the fleet's
    final map byte-matches the single-scheduler replay twin."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    rec = BindRecorder(api)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    assert a.held() == (0,) and b.held() == (1,)

    raw = {}
    _create(api, _specs(12, seed=SEED, prefix="pa", ns="ns-a"), raw)
    _create(api, _specs(12, seed=SEED + 1, prefix="pb", ns="ns-b"), raw)
    _drive(api, (a, b), clock, want_bound=24, mgr=mgr)

    assert api.binding_count == 24           # each pod bound exactly once
    # every pod landed under its OWN shard's fence: zero cross-shard noise
    assert api.fenced_rejections == 0 and a.conflicts == b.conflicts == 0
    # parks drained by the peer-bind echo, nothing leaks
    assert not a.scheduler._shard_parked and not b.scheduler._shard_parked
    assert a.scheduler.reconcile() == [] and b.scheduler.reconcile() == []
    assert _replay_twin(raw, rec.chunks, n_nodes=8) == _assignments(api)
    for inst in (a, b):
        assert inst.audit_ledger().verify()
    # the assignment gauge reflects the explicit map
    assert a.scheduler.metrics.shard_assignments.value("0") == 1.0
    assert a.scheduler.metrics.shard_assignments.value("1") == 1.0


def test_steal_mid_drain_zombie_cannot_double_bind():
    """THE fencing proof, N-way: a victim loses its shard lease while a
    full drain sits uncommitted in its dispatcher. Its late flush
    carries the stale generation — every bind is rejected server-side,
    the assumes unwind through on_bind_error, the pods re-park, and the
    thief binds each exactly once."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})

    t_enqueue = clock.t                      # the e2e clock's true origin
    _create(api, _specs(8, seed=SEED, prefix="pb", ns="ns-b"))
    b.tick()
    real_flush = b.scheduler.dispatcher.flush
    b.scheduler.dispatcher.flush = lambda *al, **kw: 0    # hold the commit
    b.scheduler.schedule_pending()
    assert len(b.scheduler.dispatcher) == 8
    assert len(b.scheduler.cache.assumed_pods) == 8

    mgr.steal(1, a)                          # generation bump = the fence
    assert mgr.steals == 1
    # the victim is a ZOMBIE: it still believes it leads until it ticks
    assert b.holds(1)

    before = api.binding_count
    b.scheduler.dispatcher.flush = real_flush
    b.scheduler.dispatcher.flush()           # the zombie's late flush
    assert api.binding_count == before, "zombie committed a bind"
    assert api.fenced_rejections > 0
    assert not b.scheduler.cache.assumed_pods         # assumes unwound
    assert b.conflicts == 8
    assert b.scheduler.metrics.cross_shard_conflicts.value("fenced") == 8
    # the unwound pods re-PARKED (not re-queued): the loser must not
    # keep re-scheduling the winner's slice
    assert len(b.scheduler._shard_parked) == 8

    b.tick()                                 # observes the loss
    assert not b.holds(1) and b.held() == ()
    _drive(api, (a,), clock, want_bound=8)
    assert api.binding_count == 8            # successor bound each ONCE
    assert a.scheduler.reconcile() == [] and b.scheduler.reconcile() == []
    # the steal latency and reason were observed
    m = a.scheduler.metrics
    assert m.shard_steals.value("steal") == 1
    assert m.shard_rebalance.count() >= 1

    # r19 stitched journeys: every stolen pod merges to exactly ONE
    # causal cross-shard timeline — fragments from both instances, zero
    # orphans, steal + bind_confirm present, timestamps monotone
    uids = [p.uid for p in api.pods.values()]
    cov = mgr.stitcher.coverage(uids)
    assert cov == {"pods": 8, "stitched": 8, "fragments": 16,
                   "orphaned": 0}
    for uid in uids:
        view = mgr.stitcher.pod(uid)
        assert set(view["instances"]) == {"sched-a", "sched-b"}
        events = [tr["event"] for tr in view["transitions"]]
        assert "steal" in events and "adopt" in events
        assert "bind_confirm" in events
        times = [tr["t"] for tr in view["transitions"]]
        assert times == sorted(times)
        # the e2e SLI clock SURVIVED the steal: the stitched origin is
        # the victim's original enqueue, not the thief's adoption
        assert view["firstEnqueue"] == t_enqueue
        # the zombie's drain fragment and the thief's carry DIFFERENT
        # fencing epochs — the stamp attributes each write to its reign
        assert len(view["fences"]) >= 2


def test_merge_collapses_ownership_with_annexed_chains():
    """N→1 merge: one instance takes every shard lease, annexes each
    predecessor's audit-chain position, and schedules the whole cluster;
    every ledger (and its handoff annex) verifies."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, _specs(10, seed=SEED, prefix="pa", ns="ns-a"))
    _create(api, _specs(10, seed=SEED + 1, prefix="pb", ns="ns-b"))
    _drive(api, (a, b), clock, want_bound=20, mgr=mgr)
    a_head = a.audit_ledger().head_hash()

    mgr.merge(b)
    assert mgr.merges == 1
    assert b.held() == (0, 1) and a.held() == ()
    # b annexed a's chain position at the moment of the handoff
    annex = b.audit_ledger().handoffs
    assert any(e["shard"] == 0 and e["head"] == a_head for e in annex)
    mgr.set_topology(1, assignments={})      # collapse the key space too
    assert mgr.shard_map().num_shards == 1

    _create(api, _specs(6, seed=SEED + 2, prefix="pc", ns="ns-a"))
    _drive(api, (b,), clock, want_bound=26)
    assert api.binding_count == 26
    for inst in (a, b):
        assert inst.audit_ledger().verify()
        assert inst.audit_ledger().verify_handoffs()
    assert b.scheduler.reconcile() == []


# -- the shard-lifecycle kill matrix -------------------------------------------


class MidFlushKiller:
    """Victim-only client facade: when armed, the next bulk bind commits
    its first half and then the 'process' dies — the half-flushed batch
    a real crash leaves behind."""

    def __init__(self, inner):
        self.inner = inner
        self.armed = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def bind_all(self, pairs, fence_token=None):
        if self.armed and len(pairs) > 1:
            self.armed = False
            self.inner.bind_all(pairs[:len(pairs) // 2],
                                fence_token=fence_token)
            raise Killed("died mid-flush")
        return self.inner.bind_all(pairs, fence_token=fence_token)


def _arm_kill(sched, phase, client=None):
    """Wire the simulated death into the chosen drain phase."""
    if phase == "host_build":
        orig = sched.builder.build

        def die(*a, **k):
            sched.builder.build = orig
            raise Killed("died in host build")
        sched.builder.build = die
    elif phase == "device":
        def die(*a, **k):
            raise Killed("died before commit")
        sched._commit_next = die
    elif phase == "commit":
        orig_flush = sched.dispatcher.flush

        def die_flush(*a, **k):
            if len(sched.dispatcher):
                raise Killed("died before the API flush")
            return orig_flush(*a, **k)
        sched.dispatcher.flush = die_flush
    elif phase == "mid_flush":
        client.armed = True
    else:                            # pragma: no cover
        raise AssertionError(phase)


@pytest.mark.slow
@pytest.mark.parametrize("phase",
                         ["host_build", "device", "commit", "mid_flush"])
def test_shard_leader_kill_matrix(phase):
    """Kill a shard leader at every drain phase, steal its orphaned
    shard, and prove the outcome indistinguishable from a serial run:
    replay-twin parity, binding_count exact (zero double-binds), zero
    oracle divergence at 100% sampling, every ledger + handoff annex
    intact."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    rec = BindRecorder(api)
    clock = Clock()
    victim_client = MidFlushKiller(api) if phase == "mid_flush" else api
    (a, b), mgr = _fleet(api, clock, clients={"sched-b": victim_client})
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})

    raw = {}
    _create(api, _specs(20, seed=100, prefix="pa", ns="ns-a"), raw)
    _create(api, _specs(20, seed=101, prefix="pb", ns="ns-b"), raw)
    _drive(api, (a, b), clock, want_bound=40, mgr=mgr)

    _create(api, _specs(24, seed=200, prefix="pc", ns="ns-b"), raw)
    _arm_kill(b.scheduler, phase, client=victim_client)
    with pytest.raises(Killed):
        b.scheduler.schedule_pending()
    # b is dead: it never ticks, renews or flushes again
    clock.t += 20.0                          # its shard lease expires
    mgr.steal(1, a)                          # peer takes the orphan
    assert a.held() == (0, 1)

    _drive(api, (a,), clock, want_bound=64)
    assert api.binding_count == 64           # zero double-binds, ever
    assert not a.scheduler.cache.assumed_pods
    assert a.scheduler.reconcile() == []
    assert _replay_twin(raw, rec.chunks, n_nodes=8) == _assignments(api)
    for sched in (a.scheduler, b.scheduler):
        for kind in ("assignment", "reason", "verdict"):
            assert sched.metrics.oracle_divergence.value(kind) == 0, kind
    for inst in (a, b):
        assert inst.audit_ledger().verify()
        assert inst.audit_ledger().verify_handoffs()
    # the annex anchors b's chain position at the steal
    assert any(e["shard"] == 1 for e in a.audit_ledger().handoffs)


def test_seeded_lease_storm_soak():
    """Chaos aims expiry/steal storms at the SHARD leases every few
    rounds: ownership thrashes, zombies get fenced, and the fleet still
    converges — zero double-binds, clean reconcile, intact ledgers."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    chaos = ChaosAPIServer(api, ChaosConfig(
        seed=SEED,
        target_leases=(shard_lease_name(0), shard_lease_name(1))))
    (a, b), mgr = _fleet(chaos, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    raw = {}
    _create(chaos, _specs(18, seed=SEED, prefix="pa", ns="ns-a"), raw)
    _create(chaos, _specs(18, seed=SEED + 1, prefix="pb", ns="ns-b"), raw)

    rng = random.Random(SEED)
    storms = 0
    for round_no in range(60):
        for inst in (a, b):
            inst.tick()
            inst.scheduler.schedule_pending()
            clock.t += 5.0
            inst.scheduler.flush_queues()
        if round_no % 7 == 3:                # a seeded strike
            storms += chaos.lease_storm(steal=rng.random() < 0.5)
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= 36 and round_no > 20:
            break
    # storms really landed, and only on the targeted shard leases
    assert storms > 0
    assert set(chaos.lease_events_by_name) <= {
        shard_lease_name(0), shard_lease_name(1)}

    bound = sum(1 for p in api.pods.values() if p.spec.node_name)
    assert bound == 36
    assert api.binding_count == 36           # zero double-binds
    assert a.scheduler.reconcile() == [] and b.scheduler.reconcile() == []
    for inst in (a, b):
        assert inst.audit_ledger().verify()
        assert not inst.scheduler.cache.assumed_pods


# -- satellite: cross-shard conflict fuzz --------------------------------------


@pytest.mark.parametrize("fuzz_seed", [SEED, SEED + 1, SEED + 2])
def test_cross_shard_conflict_fuzz(fuzz_seed):
    """Two shards race assume/bind for the SAME pods over the same node
    set: the slow loser's flush lands after a topology change moved its
    slice to the peer. The pod-level Conflict guard (and the fence, when
    the lease moved too) unwinds it — zero double-binds, clean
    reconcile, zero oracle divergence."""
    rng = random.Random(fuzz_seed)
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-x": 0,
                           "default-scheduler/ns-b": 1})
    n = rng.randint(6, 12)
    _create(api, _specs(n, seed=fuzz_seed, prefix="px", ns="ns-x"))

    # a computes a full drain for ns-x but its flush stalls (slow client)
    a.tick()
    real_flush = a.scheduler.dispatcher.flush
    a.scheduler.dispatcher.flush = lambda *al, **kw: 0
    a.scheduler.schedule_pending()
    assert len(a.scheduler.cache.assumed_pods) == n

    # the slice moves to shard 1 mid-flight; b adopts and races ahead.
    # a's shard-0 lease is UNTOUCHED, so its stale flush passes the
    # fence — the pod-level "already assigned" guard is the line.
    mgr.set_topology(2, assignments={"default-scheduler/ns-x": 1,
                                     "default-scheduler/ns-b": 1})
    b.tick()
    b.rebalance()

    flush_first = rng.random() < 0.5
    if flush_first:                          # a's flush lands FIRST: it
        a.scheduler.dispatcher.flush = real_flush       # wins the race
        a.scheduler.dispatcher.flush()
    _drive(api, (b,), clock, want_bound=n)
    if not flush_first:                      # a's flush lands LAST
        a.scheduler.dispatcher.flush = real_flush
        a.scheduler.dispatcher.flush()

    bound = [p for p in api.pods.values() if p.spec.node_name]
    assert len(bound) == n
    assert api.binding_count == n, "a cross-shard race double-bound"
    assert not a.scheduler.cache.assumed_pods
    assert not b.scheduler.cache.assumed_pods
    if not flush_first:
        # the loser saw n pod-level conflicts, all unwound + re-parked
        assert a.conflicts == n
        assert a.scheduler.metrics.cross_shard_conflicts.value(
            "conflict") + a.scheduler.metrics.cross_shard_conflicts.value(
            "fenced") >= n
    a.rebalance()
    assert a.scheduler.reconcile() == [] and b.scheduler.reconcile() == []
    for sched in (a.scheduler, b.scheduler):
        for kind in ("assignment", "reason", "verdict"):
            assert sched.metrics.oracle_divergence.value(kind) == 0, kind


# -- satellite: the standby sync-vs-ingest race --------------------------------


def test_standby_sync_races_watch_ingest():
    """Regression (ISSUE 17 bugfix): StandbyScheduler.sync()'s host
    rebuild used to iterate workload state WHILE watch handlers mutated
    it — a torn re-tensorize. Both sides now hold the scheduler's
    ingest lock; a concurrent create storm during a sync loop must
    neither raise nor corrupt the snapshot."""
    api = APIServer()
    _nodes(api, n=4, cpu=32, mem="64Gi")
    clock = Clock()
    leader = _audited(_no_sleep(Scheduler(api, batch_size=16, clock=clock)))
    el = LeaderElector(api, "sched-a", clock=clock)
    fence_dispatcher(leader.dispatcher, el)
    assert el.tick() is True
    _create(api, _specs(4, seed=SEED, prefix="warm"))
    leader.schedule_pending()

    inner = _audited(_no_sleep(Scheduler(api, batch_size=16, clock=clock)))
    standby = StandbyScheduler(api, identity="sched-b", clock=clock,
                               ledger=leader.audit.ledger, scheduler=inner)
    errors = []
    stop = threading.Event()

    def feeder():
        i = 0
        try:
            while not stop.is_set() and i < 400:
                _create(api, [(f"race{i}", "default", 100, 64)])
                i += 1
        except Exception as exc:             # pragma: no cover
            errors.append(exc)

    t = threading.Thread(target=feeder)
    t.start()
    try:
        for _ in range(40):
            standby.sync()                   # full rebuild, every loop
    finally:
        stop.set()
        t.join()
    assert not errors
    # post-race: one more locked sync + a full resync leave a snapshot
    # consistent with the store (every unbound pod accounted for)
    standby.sync()
    inner.resync()
    pending, _ = inner.queue.pending_pods()
    unbound = sum(1 for p in api.pods.values() if not p.spec.node_name)
    assert len(pending) == unbound


# -- satellite: shard-aware chaos targeting ------------------------------------


def test_chaos_lease_targeting_scopes_faults():
    """target_leases narrows the expiry storm to named leases: the
    untargeted shard's lease never ages, and the per-name counters
    export exactly what was hit."""
    api = APIServer()
    chaos = ChaosAPIServer(api, ChaosConfig(
        seed=SEED, lease_expire_rate=1.0,
        target_leases=(shard_lease_name(0),)))
    chaos.acquire_lease(shard_lease_name(0), "sched-a", 0.0)
    chaos.acquire_lease(shard_lease_name(1), "sched-b", 0.0)
    for t in range(1, 6):
        chaos.renew_lease(shard_lease_name(0), "sched-a", float(t))
        chaos.renew_lease(shard_lease_name(1), "sched-b", float(t))
    assert chaos.lease_events_by_name.get(shard_lease_name(0), 0) > 0
    assert shard_lease_name(1) not in chaos.lease_events_by_name


def test_chaos_lease_storm_is_deterministic():
    """lease_storm strikes every targeted lease at once; steal=True
    swaps the holder AND bumps the generation, so every outstanding
    fence pair for that shard goes stale in one stroke."""
    api = APIServer()
    chaos = ChaosAPIServer(api, ChaosConfig(seed=SEED))
    for sid in range(3):
        api.acquire_lease(shard_lease_name(sid), f"sched-{sid}", 100.0)
    gens = {sid: api.get_lease(shard_lease_name(sid)).generation
            for sid in range(3)}

    struck = chaos.lease_storm(steal=True)
    assert struck == 3
    for sid in range(3):
        lease = api.get_lease(shard_lease_name(sid))
        assert lease.holder_identity.startswith("chaos-thief")
        assert lease.generation == gens[sid] + 1
    assert sum(chaos.lease_events_by_name.values()) == 3

    # expiry flavour: holder unchanged, renewTime aged past the duration
    api2 = APIServer()
    chaos2 = ChaosAPIServer(api2, ChaosConfig(seed=SEED))
    api2.acquire_lease(shard_lease_name(0), "sched-a", 100.0,
                       lease_duration_s=15.0)
    assert chaos2.lease_storm() == 1
    lease = api2.get_lease(shard_lease_name(0))
    assert lease.holder_identity == "sched-a"
    assert lease.renew_time < 100.0 - 15.0


def test_chaos_asymmetric_identity_latency():
    """for_identity() views give ONE shard client a private latency
    distribution while peers ride the base script — and the per-identity
    totals are exported for the matrix to assert on."""
    api = APIServer()
    _nodes(api, n=2)
    slept = []
    chaos = ChaosAPIServer(api, ChaosConfig(
        seed=SEED,
        identity_latency={"sched-b": (1.0, 0.01, 0.01)}),
        sleep=slept.append)
    view_a = chaos.for_identity("sched-a")
    view_b = chaos.for_identity("sched-b")

    _create(view_a, _specs(3, seed=1, prefix="fast"))
    assert not slept and not chaos.identity_latency_total

    _create(view_b, _specs(3, seed=2, prefix="slow"))
    assert len(slept) == 3
    assert chaos.identity_latency_total["sched-b"] == pytest.approx(0.03)
    assert "sched-a" not in chaos.identity_latency_total
    # non-latency verbs pass straight through the view
    assert view_b.get_lease("nope") is None


# -- satellite: observability --------------------------------------------------


def test_debug_shards_endpoint():
    """/debug/shards serves the manager's topology + per-shard lease
    view; without a manager it degrades to the instance's slice."""
    import json
    import urllib.request

    from kubernetes_tpu.server import SchedulerServer

    api = APIServer()
    _nodes(api, n=2)
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0})

    srv = SchedulerServer(a.scheduler, shard_manager=mgr).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/shards") as r:
            payload = json.loads(r.read())
    finally:
        srv.stop()
    assert payload["numShards"] == 2
    assert payload["assignments"] == {"default-scheduler/ns-a": 0}
    assert payload["leases"]["0"]["holder"] == "sched-a"
    assert payload["leases"]["1"]["holder"] == "sched-b"
    assert payload["leases"]["1"]["generation"] >= 1
    assert {i["identity"] for i in payload["instances"]} \
        == {"sched-a", "sched-b"}

    srv2 = SchedulerServer(a.scheduler).start()   # no manager: fallback
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv2.port}/debug/shards") as r:
            fallback = json.loads(r.read())
    finally:
        srv2.stop()
    assert fallback["numShards"] is None
    assert fallback["shardIds"] == [0]


def test_flight_record_carries_shard_tag():
    """Every drain committed while holding shard leases is tagged with
    the owned shard ids in the flight ring (and a plain scheduler's
    records stay untagged)."""
    api = APIServer()
    _nodes(api, n=4, cpu=32, mem="64Gi")
    clock = Clock()
    (a, b), mgr = _fleet(api, clock)
    mgr.split(2, owners={0: a, 1: b},
              assignments={"default-scheduler/ns-a": 0,
                           "default-scheduler/ns-b": 1})
    _create(api, _specs(4, seed=SEED, prefix="pa", ns="ns-a"))
    _drive(api, (a, b), clock, want_bound=4, mgr=mgr)
    records = a.scheduler.flight.dump()
    assert records and all(r["shard"] == [0] for r in records)

    plain = _audited(_no_sleep(Scheduler(APIServer(), batch_size=8)))
    _nodes(plain.client, n=2)
    _create(plain.client, _specs(2, seed=SEED, prefix="q"))
    plain.schedule_pending()
    assert all(r["shard"] == [] for r in plain.flight.dump())
