"""Chaos: seeded fault injection against the resilient commit pipeline.

The standing correctness gate this file establishes (ISSUE 2): a seeded
fault script — transient bind/patch/delete errors, added latency, a node
flap — must leave the final (pod → node) assignment IDENTICAL to the
fault-free run of the same workload, because retries absorb every
transient and terminal errors route through forget/requeue. Plus: the
device-tier circuit breaker (XLA fault → host path → cooldown →
re-probe), watch-loss recovery via resync(), and a long mixed soak
(marked slow; CHAOS_SEED=N overrides the script seed).
"""

import dataclasses
import os
import random

import pytest

import kubernetes_tpu.scheduler as sched_mod
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.chaos import ChaosAPIServer, ChaosConfig
from kubernetes_tpu.testing.wrappers import make_node, make_pod

SEED = int(os.environ.get("CHAOS_SEED", "7"))


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _no_sleep(sched):
    """Retries must not burn wall clock in tests."""
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _pod_specs(n, seed, prefix="p"):
    """Deterministic mixed workload: (name, cpu_m, mem_mi) triples."""
    rng = random.Random(seed)
    return [(f"{prefix}{i}", 250 * rng.randint(1, 6), 512 * rng.randint(1, 4))
            for i in range(n)]


def _create(api, specs):
    for name, cpu, mem in specs:
        api.create_pod(make_pod(name)
                       .req({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj())


def _nodes(api, n=6, cpu=16, mem="32Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _cordon(api, name, value):
    node = api.nodes[name]
    spec = dataclasses.replace(node.spec, unschedulable=value)
    api.update_node(dataclasses.replace(node, spec=spec))


def _drive_to_quiescence(api, sched, clock, want_bound, max_rounds=60):
    """Advance time + flush until every pod binds (backoffs expire in
    between); asserts progress terminates."""
    for _ in range(max_rounds):
        sched.schedule_pending()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= want_bound:
            return
        clock.t += 10.0
        sched.flush_queues()
    raise AssertionError(
        f"did not quiesce: "
        f"{sum(1 for p in api.pods.values() if p.spec.node_name)}"
        f"/{want_bound} bound, pending={sched.pending_summary()}")


def _assignments(api):
    return {uid: p.spec.node_name for uid, p in api.pods.items()}


def _run_parity_workload(api, audit=False):
    """The parity workload: two clean waves with a mid-run node flap (the
    chaotic twin only — the store is identical again before the next
    call), then a cordon-everything wave that strands a whole batch
    (Unschedulable status patches flow in BOTH runs), then uncordon +
    drain to fully bound."""
    clock = Clock()
    sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
    if audit:
        # shadow audit forced onto EVERY drain, replays inline
        sched.audit.sample_rate = 1.0
        sched.audit.synchronous = True
    _create(api, _pod_specs(20, seed=100, prefix="a"))
    sched.schedule_pending()
    if isinstance(api, ChaosAPIServer):
        api.flap_node("n2")   # the one scripted node flap, mid-run
    _create(api, _pod_specs(16, seed=200, prefix="b"))
    sched.schedule_pending()
    # cordon EVERY node: the next wave fully strands → status patches
    # (the patch-verb fault path) flow through the dispatcher
    for name in list(api.nodes):
        _cordon(api, name, True)
    _create(api, _pod_specs(18, seed=300, prefix="c"))
    sched.schedule_pending()
    for name in list(api.nodes):
        _cordon(api, name, False)
    clock.t += 40.0
    sched.flush_queues()
    _drive_to_quiescence(api, sched, clock, want_bound=54)
    return sched


def test_chaos_parity():
    """Acceptance gate: ≥5% transient error rate on bind/patch/delete +
    one node flap ⇒ all pods bind and the assignment map is identical to
    the fault-free run."""
    clean_api = APIServer()
    _nodes(clean_api)
    _run_parity_workload(clean_api)
    clean = _assignments(clean_api)
    assert len(clean) == 54 and all(clean.values()), \
        "fault-free run must bind everything"

    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED,
        error_rates={"bind": 0.10, "patch": 0.10, "delete": 0.10},
        latency_rate=0.25, latency_seconds=(0.001, 0.05)))
    _nodes(chaos)
    sched = _run_parity_workload(chaos)
    chaotic = _assignments(chaos.inner)

    assert chaotic == clean
    # the script must have actually fired: injected transients were
    # retried (not surfaced), the flap really happened, latency was drawn
    assert chaos.injected_errors["bind"] > 0
    assert chaos.injected_errors["patch"] > 0
    assert chaos.node_flaps == 1
    assert chaos.injected_latency_total > 0
    assert sched.dispatcher.retries > 0
    assert sched.metrics.api_retries.value("pod_binding") > 0
    # retries absorbed every transient: zero terminal dispatcher errors
    assert sched.dispatcher.errors == 0
    assert not sched.cache.assumed_pods


def test_chaos_audit_zero_divergence():
    """ISSUE 10 satellite: the shadow-oracle audit at 100% sampling sees
    ZERO divergence under the seeded fault script — faults degrade
    paths (retries, fallbacks), never decisions. The audited drains'
    hash chain stays intact through the churn."""
    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED,
        error_rates={"bind": 0.10, "patch": 0.10, "delete": 0.10},
        latency_rate=0.25, latency_seconds=(0.001, 0.05)))
    _nodes(chaos)
    sched = _run_parity_workload(chaos, audit=True)
    m = sched.metrics
    for kind in ("assignment", "reason", "verdict"):
        assert m.oracle_divergence.value(kind) == 0, kind
    assert m.shadow_audit_drains.value("clean") >= 3
    assert m.shadow_audit_drains.value("divergent") == 0
    assert chaos.injected_errors["bind"] > 0   # the script really fired
    assert sched.audit.ledger.verify()


def test_chaos_audit_catches_injected_perturbation():
    """The audit must be provably able to FAIL: a deliberately injected
    wrong-but-valid decision (the test-only perturbation hook — the
    stand-in for a buggy learned score column, ROADMAP item 5) is
    caught, counted in oracle_divergence_total and rendered in
    /debug/audit."""
    import json
    import urllib.request

    from kubernetes_tpu.server import SchedulerServer
    api = APIServer()
    _nodes(api)
    clock = Clock()
    sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    flips = []

    def perturb(pd, out):
        # flip the LAST assigned pod's node: by then load differentiates
        # the scores, so the flip is outside the oracle's argmax tie set
        if flips:
            return
        for i in range(len(out) - 1, -1, -1):
            if out[i] >= 0:
                out[i] = (out[i] + 1) % 6   # another real node
                flips.append(i)
                break
    sched._test_assignment_perturb = perturb
    _create(api, _pod_specs(16, seed=900, prefix="x"))
    sched.schedule_pending()
    sched.audit.flush()
    assert flips, "the perturbation must have fired"
    assert sched.metrics.oracle_divergence.value("assignment") >= 1
    assert sched.metrics.shadow_audit_drains.value("divergent") >= 1
    srv = SchedulerServer(sched).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/audit?details=1",
                timeout=10) as r:
            payload = json.loads(r.read().decode())
        divergent = [rec for rec in payload["records"]
                     if rec["outcome"] == "divergent"]
        assert divergent and divergent[0]["diffs"]["assignment"]
        assert payload["chainValid"]
    finally:
        srv.stop()


def _run_wave_parity_workload(api):
    """Wave-path fault-parity twin (ISSUE 3): group pods (spread +
    anti-affinity) ride the speculative wave kernels through the same
    seeded fault script; waves must be fault-transparent — the resident
    device carry either commits exactly or degrades to the host oracle,
    never half-applies."""
    clock = Clock()
    sched = _no_sleep(Scheduler(api, batch_size=32, clock=clock))
    sched.wave_min_span = 4
    for i in range(18):
        api.create_pod(make_pod(f"ws{i}")
                       .req({"cpu": "500m", "memory": "512Mi"})
                       .label("app", "wsp")
                       .spread_constraint(2, "topology.kubernetes.io/zone",
                                          "DoNotSchedule", {"app": "wsp"})
                       .obj())
    sched.schedule_pending()
    if isinstance(api, ChaosAPIServer):
        api.flap_node("n1")
    for i in range(12):
        api.create_pod(make_pod(f"wa{i}")
                       .req({"cpu": "500m", "memory": "512Mi"})
                       .label("anti", "wv")
                       .pod_affinity("kubernetes.io/hostname",
                                     {"anti": "wv"}, anti=True)
                       .obj())
    sched.schedule_pending()
    clock.t += 40.0
    sched.flush_queues()
    _drive_to_quiescence(api, sched, clock, want_bound=24)
    return sched


def test_chaos_wave_parity():
    """Fault-parity gate over the WAVE path: seeded transient faults on
    bind/patch + a node flap while group drains run through run_wave ⇒
    assignments identical to the fault-free run."""
    clean_api = APIServer()
    _nodes(clean_api)
    clean_sched = _run_wave_parity_workload(clean_api)
    clean = _assignments(clean_api)
    assert clean_sched.metrics.wave_placement_waves.value() > 0, \
        "the wave path must actually engage"

    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED,
        error_rates={"bind": 0.10, "patch": 0.10, "delete": 0.10},
        latency_rate=0.25, latency_seconds=(0.001, 0.05)))
    _nodes(chaos)
    sched = _run_wave_parity_workload(chaos)
    chaotic = _assignments(chaos.inner)

    assert chaotic == clean
    assert chaos.injected_errors["bind"] > 0
    assert sched.dispatcher.retries > 0
    assert sched.dispatcher.errors == 0
    assert not sched.cache.assumed_pods


def test_conflict_storm_routes_through_forget_requeue():
    """Conflicts are TERMINAL: no retry — forget the assumed pod, requeue
    with error backoff, and still converge to fully bound."""
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(seed=SEED, conflict_rate=0.3))
    _nodes(chaos, n=4)
    sched = _no_sleep(Scheduler(chaos, batch_size=16, clock=clock))
    _create(chaos, _pod_specs(24, seed=400))
    _drive_to_quiescence(chaos, sched, clock, want_bound=24)
    assert chaos.injected_conflicts > 0
    assert sched.error_count > 0          # each storm hit the forget path
    assert sched.dispatcher.retries == 0  # terminal ⇒ never retried
    assert not sched.cache.assumed_pods
    assert sched.reconcile() == []


def test_watch_loss_resync_recovers():
    """Dropped watch events corrupt the scheduler's view (missed pod
    adds, missed bind confirmations, missed node adds); resync() rebuilds
    cache+queue from a fresh LIST and the cluster converges clean."""
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED, drop_watch_rate=0.3))
    sched = _no_sleep(Scheduler(chaos, batch_size=16, clock=clock))
    _nodes(chaos, n=5)          # registered AFTER the scheduler: droppable
    _create(chaos, _pod_specs(30, seed=500))
    sched.schedule_pending()
    assert chaos.dropped_events > 0
    # stop the bleeding, then recover from the store's truth
    chaos.cfg.drop_watch_rate = 0.0
    sched.resync()
    assert sched.metrics.resyncs.value() == 1
    _drive_to_quiescence(chaos, sched, clock, want_bound=30)
    assert not sched.cache.assumed_pods
    assert sched.debugger.compare() == []
    assert sched.reconcile() == []


def test_device_fault_circuit_breaker(monkeypatch):
    """Forced kernel fault: the batch completes on the host path (no
    crash, no lost pods); K consecutive faults open the breaker; the
    cooldown re-probes the device tier and closes it — both transitions
    visible in metrics."""
    clock = Clock()
    api = APIServer()
    _nodes(api, n=4)
    sched = _no_sleep(Scheduler(api, batch_size=16, clock=clock))
    m = sched.metrics

    real_run_batch = sched_mod.run_batch
    real_run_uniform = sched_mod.run_uniform

    def boom(*_a, **_k):
        raise RuntimeError("injected xla fault")

    monkeypatch.setattr(sched_mod, "run_batch", boom)
    monkeypatch.setattr(sched_mod, "run_uniform", boom)

    bound = 0
    for wave in range(sched.device_fault_threshold):
        _create(api, _pod_specs(6, seed=600 + wave, prefix=f"w{wave}-"))
        bound += 6
        sched.schedule_pending()
        assert sum(1 for p in api.pods.values() if p.spec.node_name) == bound
    assert sched.device_fallbacks == sched.device_fault_threshold
    assert m.circuit_breaker_transitions.value("open") == 1
    assert m.device_fallbacks.value("dispatch") == sched.device_fault_threshold

    # breaker open: drains route to the host oracle WITHOUT touching the
    # (still broken) device tier
    _create(api, _pod_specs(6, seed=690, prefix="open-"))
    bound += 6
    sched.schedule_pending()
    assert sum(1 for p in api.pods.values() if p.spec.node_name) == bound
    assert m.device_fallbacks.value("circuit_open") >= 1
    assert m.circuit_breaker_transitions.value("open") == 1  # no flapping

    # device recovers; cooldown expires → probe drain closes the breaker
    monkeypatch.setattr(sched_mod, "run_batch", real_run_batch)
    monkeypatch.setattr(sched_mod, "run_uniform", real_run_uniform)
    clock.t += sched.device_fault_cooldown + 1.0
    before = sched.device_batches
    _create(api, _pod_specs(6, seed=700, prefix="probe-"))
    bound += 6
    sched.schedule_pending()
    assert sum(1 for p in api.pods.values() if p.spec.node_name) == bound
    assert sched.device_batches > before          # device tier re-enabled
    assert m.circuit_breaker_transitions.value("closed") == 1
    assert sched.reconcile() == []


def test_invalid_assignment_tensor_falls_back(monkeypatch):
    """A garbage assignment tensor (the argmax of a non-finite score
    column) must never reach the cache: the drain degrades to the host
    oracle and every pod still binds."""
    clock = Clock()
    api = APIServer()
    _nodes(api, n=4)
    sched = _no_sleep(Scheduler(api, batch_size=16, clock=clock))
    real_run_batch = sched_mod.run_batch

    def corrupt(*a, **k):
        import jax.numpy as jnp
        carry, assigns = real_run_batch(*a, **k)
        return carry, jnp.full_like(assigns, 1 << 20)

    monkeypatch.setattr(sched_mod, "run_batch", corrupt)
    _create(api, _pod_specs(8, seed=800))
    sched.schedule_pending()
    assert sum(1 for p in api.pods.values() if p.spec.node_name) == 8
    assert sched.metrics.device_fallbacks.value("invalid_assignment") >= 1
    assert not sched.cache.assumed_pods


@pytest.mark.slow
def test_chaos_soak():
    """Long mixed soak under the FULL fault script (transients, conflict
    storms, latency, node flaps, dropped+duplicated watch events with
    periodic resync): no crash, no lost pods, clean convergence.
    CHAOS_SEED=N replays a specific script."""
    rng = random.Random(SEED)
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED,
        error_rates={"bind": 0.08, "patch": 0.08, "delete": 0.08,
                     "create": 0.02},
        conflict_rate=0.05,
        latency_rate=0.2, latency_seconds=(0.001, 0.05),
        drop_watch_rate=0.03, dup_watch_rate=0.03,
        node_flap_rate=0.02))
    sched = _no_sleep(Scheduler(chaos, batch_size=32, clock=clock))
    # ISSUE 10: the soak runs with the shadow audit forced onto EVERY
    # drain — seeded faults must produce zero oracle divergence
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    n_nodes = 24    # ~380 live pods by the end: size the cluster for them
    _nodes(chaos, n=n_nodes, cpu=32, mem="64Gi")
    seq = 0
    live = []
    dropped_seen = 0
    for wave in range(120):
        action = rng.random()
        if action < 0.55:
            for _ in range(rng.randint(3, 10)):
                name = f"s{seq}"
                seq += 1
                try:
                    chaos.create_pod(make_pod(name).req(
                        {"cpu": f"{rng.randint(1, 6) * 250}m",
                         "memory": f"{rng.randint(1, 4) * 512}Mi"}).obj())
                except Exception:
                    continue    # injected create fault: client gives up
                live.append(f"default/{name}")
        elif action < 0.72 and live:
            for _ in range(rng.randint(1, 4)):
                if not live:
                    break
                uid = live.pop(rng.randrange(len(live)))
                if uid in chaos.pods:
                    try:
                        chaos.delete_pod(uid)
                    except Exception:
                        live.append(uid)    # injected fault: still alive
        elif action < 0.85:
            chaos.flap_node(f"n{rng.randrange(n_nodes)}")
        else:
            clock.t += rng.choice([5.0, 40.0, 400.0])
            sched.flush_queues()
        sched.schedule_pending()
        if chaos.dropped_events > dropped_seen:
            # the watch layer reported loss since last wave: relist
            sched.resync()
            dropped_seen = chaos.dropped_events
        for p in chaos.pods.values():
            if p.spec.node_name:
                assert p.spec.node_name in chaos.nodes
    # final convergence: stop watch chaos (a real client resyncs after
    # loss; ours did above), drain everything outstanding
    chaos.cfg.drop_watch_rate = chaos.cfg.dup_watch_rate = 0.0
    chaos.cfg.node_flap_rate = 0.0
    sched.resync()
    want = len(chaos.pods)
    _drive_to_quiescence(chaos, sched, clock, want_bound=want,
                         max_rounds=120)
    assert not sched.cache.assumed_pods
    assert sched.debugger.compare() == []
    assert chaos.injected_errors["bind"] > 0
    assert chaos.node_flaps > 0
    assert chaos.dropped_events > 0
    # shadow audit over the whole soak: many drains audited, none
    # divergent, and the ledger's hash chain survived the churn
    m = sched.metrics
    for kind in ("assignment", "reason", "verdict"):
        assert m.oracle_divergence.value(kind) == 0, kind
    assert m.shadow_audit_drains.value("clean") > 10
    assert m.shadow_audit_drains.value("divergent") == 0
    assert sched.audit.ledger.verify()
