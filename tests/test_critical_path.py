"""Critical-path observatory + device cost model (ISSUE 20).

Covers the full stack the tentpole ships:

- `attribute_drain`: the verdict argmax over the CAUSES taxonomy, the
  comms-share split of the device window, the CAUSES-order tiebreak,
  the all-zero idle fallback, and the binding chain's segments;
- `aggregate` / `ceiling_factor`: the window histogram, the
  dominant-by-seconds (not modal) rule, and the headroom projection
  with its 100x cap;
- `phase_shares`: THE one stage-share implementation bench.py's
  phase_pct/host_share summary and the pipeline occupancy block both
  call (the ISSUE 20 unification bugfix) — plus the live-pipeline
  agreement regression;
- `attribute_delta`: per-drain-normalized differential attribution
  (tools/bench_compare.py --attribute);
- the device cost model end to end: a forced fresh compile lands
  XLA/host-estimated flops+bytes rows in `cost_view()` and the
  /debug/kernels snapshot;
- verdict stamping end to end: FlightRecords carry `criticalPath`, the
  scheduler_critical_path_seconds / scheduler_bottleneck_drains_total
  families move, and the gate off means no stamp, no movement, 404;
- /debug/criticalpath over a live SchedulerServer (last-N window +
  aggregate, ?limit=N, 404 with the gate off);
- tools/check.py `cost_model_gaps` (the exit-2 config rule mirroring
  observatory_gaps);
- stall attribution under the streaming pipeline (ISSUE 20 satellite):
  backpressure in EACH direction yields a `backpressure` verdict whose
  stall seconds are conserved against the pipeline's own stall clock
  and consistent with scheduler_pipeline_backpressure_total, while
  lock-step drains can NEVER carry one;
- the slow-marked throughput gate: CriticalPathObservatory ON within
  5% of OFF at 5k nodes (the ISSUE 13/14 gate shape).
"""

import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubernetes_tpu.backend.apiserver import APIServer  # noqa: E402
from kubernetes_tpu.config import KubeSchedulerConfiguration  # noqa: E402
from kubernetes_tpu.perf import costmodel  # noqa: E402
from kubernetes_tpu.perf import observatory as obs_mod  # noqa: E402
from kubernetes_tpu.perf.costmodel import (CostModel,  # noqa: E402
                                           classify, host_estimate,
                                           modeled_seconds)
from kubernetes_tpu.perf.critical_path import (CAUSES,  # noqa: E402
                                               aggregate, attribute_delta,
                                               attribute_drain,
                                               ceiling_factor, phase_shares)
from kubernetes_tpu.perf.observatory import GLOBAL as OBS  # noqa: E402
from kubernetes_tpu.pipeline import STAGES, StreamingPipeline  # noqa: E402
from kubernetes_tpu.scheduler import Scheduler  # noqa: E402
from kubernetes_tpu.server import SchedulerServer  # noqa: E402
from kubernetes_tpu.testing.wrappers import make_node, make_pod  # noqa: E402

SEED = 2099


# ---------------------------------------------------------------------------
# helpers (tests/test_pipeline.py idiom)


def _nodes(api, n=8, cpu=64, mem="128Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _specs(n, seed, prefix="p"):
    rng = random.Random(seed)
    return [(f"{prefix}{i}", "default", 250 * rng.randint(1, 6),
             512 * rng.randint(1, 4)) for i in range(n)]


def _pods(specs):
    return [make_pod(name, namespace=ns).req(
        {"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj()
        for name, ns, cpu, mem in specs]


def _sched(client, batch_size=64, **kw):
    sched = Scheduler(client, batch_size=batch_size, **kw)
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _await(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return False


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _verdicts(sched):
    return [d["criticalPath"] for d in sched.flight.dump()
            if d.get("criticalPath")]


# ---------------------------------------------------------------------------
# attribute_drain


class TestAttributeDrain:
    def test_verdict_is_argmax_over_causes(self):
        cp = attribute_drain({"host_build": 2.0, "device_dispatch": 1.0,
                              "device_wait": 0.25, "commit": 0.5})
        assert cp["verdict"] == "host_build"
        assert cp["causes"] == {"host_build": 2.0, "device_compute": 1.0,
                                "device_comms": 0.0, "commit": 0.5,
                                "backpressure": 0.0, "idle": 0.25}
        assert set(cp["causes"]) == set(CAUSES)

    def test_comms_share_splits_the_device_window(self):
        cp = attribute_drain({"device_dispatch": 1.0}, comms_share=0.6)
        assert cp["causes"]["device_comms"] == pytest.approx(0.6)
        assert cp["causes"]["device_compute"] == pytest.approx(0.4)
        assert cp["verdict"] == "device_comms"
        # out-of-range shares clamp instead of inventing negative time
        hi = attribute_drain({"device_dispatch": 1.0}, comms_share=1.5)
        assert hi["causes"]["device_comms"] == pytest.approx(1.0)
        assert hi["causes"]["device_compute"] == 0.0
        lo = attribute_drain({"device_dispatch": 1.0}, comms_share=-3.0)
        assert lo["causes"]["device_compute"] == pytest.approx(1.0)

    def test_exact_tie_breaks_in_causes_order(self):
        cp = attribute_drain({"host_build": 1.0, "commit": 1.0})
        assert cp["verdict"] == "host_build"
        cp = attribute_drain({"commit": 1.0, "device_wait": 1.0})
        assert cp["verdict"] == "commit"

    def test_all_zero_record_is_idle(self):
        cp = attribute_drain({})
        assert cp["verdict"] == "idle"
        assert all(s == 0.0 for s in cp["causes"].values())
        assert cp["chain"] == []

    def test_backpressure_seconds_become_the_verdict(self):
        cp = attribute_drain({"host_build": 0.01, "commit": 0.02},
                             backpressure_s=0.5)
        assert cp["verdict"] == "backpressure"
        assert cp["causes"]["backpressure"] == pytest.approx(0.5)
        spans = {seg["span"]: seg for seg in cp["chain"]}
        assert spans["backpressure_stall"]["cause"] == "backpressure"
        assert spans["backpressure_stall"]["seconds"] == pytest.approx(0.5)

    def test_chain_segments_and_residuals(self):
        phases = {"host_build": 0.10, "host_snapshot": 0.03,
                  "host_tensorize": 0.05, "device_dispatch": 0.20,
                  "device_wait": 0.04, "commit": 0.06}
        kernels = {"run_uniform": 0.12, "run_wave": 0.05}
        cp = attribute_drain(phases, kernels=kernels)
        spans = {seg["span"]: seg for seg in cp["chain"]}
        # named host children + the residual cover host_build exactly
        assert spans["host_snapshot"]["cause"] == "host_build"
        assert spans["host_other"]["seconds"] == pytest.approx(0.02)
        # kernel lanes + device_other cover device_dispatch exactly
        assert spans["kernel:run_uniform"]["cause"] == "device_compute"
        assert spans["device_other"]["seconds"] == pytest.approx(0.03)
        assert spans["device_wait"]["cause"] == "idle"
        assert spans["commit"]["cause"] == "commit"
        # zero segments are dropped: no host_group_seed / host_cache rows
        assert "host_group_seed" not in spans
        assert "host_cache" not in spans
        assert all(seg["seconds"] > 0 for seg in cp["chain"])
        # a comms-dominated drain tags the kernel lanes device_comms
        comms = attribute_drain(phases, kernels=kernels, comms_share=0.9)
        spans = {seg["span"]: seg for seg in comms["chain"]}
        assert spans["kernel:run_wave"]["cause"] == "device_comms"


# ---------------------------------------------------------------------------
# aggregate / ceiling_factor


class TestAggregate:
    def test_dominant_is_by_seconds_not_modal(self):
        # two quick host_build drains must not outvote one giant commit
        vs = [attribute_drain({"host_build": 0.01}),
              attribute_drain({"host_build": 0.01}),
              attribute_drain({"commit": 1.0})]
        agg = aggregate(vs)
        assert agg["drains"] == 3
        assert agg["verdicts"] == {"commit": 1, "host_build": 2}
        assert agg["dominant"] == "commit"
        # ceiling: 1.02 total / 0.02 rest = 51x
        assert agg["ceiling_factor"] == pytest.approx(51.0, rel=1e-3)

    def test_empty_and_malformed_entries(self):
        agg = aggregate([])
        assert agg["drains"] == 0 and agg["verdicts"] == {}
        assert "dominant" not in agg and "ceiling_factor" not in agg
        agg = aggregate([None, {}, {"verdict": ""},
                         attribute_drain({"commit": 0.5})])
        assert agg["drains"] == 1
        assert agg["dominant"] == "commit"

    def test_ceiling_factor_formula_and_cap(self):
        causes = {"host_build": 3.0, "commit": 1.0}
        assert ceiling_factor(causes, "host_build") == pytest.approx(4.0)
        # the dominant cause IS the cycle: capped, not infinite
        assert ceiling_factor({"commit": 1.0}, "commit") == 100.0
        assert ceiling_factor({}, "commit") == 1.0


# ---------------------------------------------------------------------------
# phase_shares — the ONE share implementation (ISSUE 20 satellite)


class TestPhaseShares:
    def test_lockstep_shares_sum_to_one(self):
        parts = {"host_build": 0.6, "device": 0.3, "commit": 0.1}
        out = phase_shares(parts)
        assert out["total"] == pytest.approx(1.0)
        assert out["occupancy"] == pytest.approx(1.0)
        assert sum(out["shares"].values()) == pytest.approx(1.0, abs=1e-3)
        assert out["shares"]["host_build"] == pytest.approx(0.6)
        assert out["host_share"] == pytest.approx(0.7)

    def test_wall_denominator_allows_overlap(self):
        # a pipeline window: stages overlap, so busy sums past the wall
        parts = {"ingest": 0.8, "device": 0.9, "commit": 0.5}
        out = phase_shares(parts, wall=1.0)
        assert out["occupancy"] == pytest.approx(2.2)
        assert out["shares"]["device"] == pytest.approx(0.9)
        # zero/None wall falls back to the segments' own sum
        assert phase_shares(parts, wall=0.0)["occupancy"] == 1.0

    def test_bench_and_pipeline_surfaces_agree(self):
        """The regression the satellite exists for: bench.py's
        phase_pct/host_share and the pipeline occupancy block must
        derive from the SAME math over the same window."""
        parts = {"host_build": 0.25, "device": 0.5, "commit": 0.25}
        bench = phase_shares(parts)                 # bench.py summary path
        pipe = phase_shares(parts, wall=1.0)        # pipeline stats path
        # same window (wall == busy sum) → identical shares + host share
        assert bench["shares"] == pipe["shares"]
        assert bench["host_share"] == pipe["host_share"]
        # and bench's percentage rendering is a pure rescale of the same
        # fractions, not a second implementation
        phase_pct = {k: round(100.0 * v, 1)
                     for k, v in bench["shares"].items()}
        assert phase_pct == {"host_build": 25.0, "device": 50.0,
                             "commit": 25.0}

    def test_live_pipeline_stats_use_phase_shares(self):
        """End to end: the /debug/pipeline occupancy block's shares are
        busy/wall under the shared helper — shares, occupancy and busy
        seconds must stay mutually consistent on a real window."""
        api = APIServer()
        _nodes(api)
        sched = _sched(api)
        sched.prime()
        pipe = StreamingPipeline(sched)
        pipe.start()
        try:
            pipe.feed(_pods(_specs(48, SEED)), close=True)
            pipe.drain(timeout=60.0)
        finally:
            pipe.stop()
        st = pipe.stats()
        assert not pipe.errors
        assert set(st["busyShares"]) == set(STAGES)
        busy_sum = sum(st["busySeconds"].values())
        assert busy_sum > 0 and st["occupancy"] > 0
        for stage in STAGES:
            # share[s]/occupancy == busy[s]/sum(busy): both ratios come
            # from the one phase_shares call over the same wall
            assert st["busyShares"][stage] / st["occupancy"] == \
                pytest.approx(st["busySeconds"][stage] / busy_sum, abs=0.02)


# ---------------------------------------------------------------------------
# attribute_delta


class TestAttributeDelta:
    def test_names_the_cause_that_moved_per_drain(self):
        base = aggregate([attribute_drain({"host_build": 0.1,
                                           "commit": 0.1})
                          for _ in range(4)])
        # twice the drains — per-drain normalization must see through it
        new = aggregate([attribute_drain({"host_build": 0.1,
                                          "commit": 0.3})
                         for _ in range(8)])
        moved = attribute_delta(base, new)
        assert moved["cause"] == "commit"
        assert moved["base_s"] == pytest.approx(0.1)
        assert moved["new_s"] == pytest.approx(0.3)
        assert moved["ratio"] == pytest.approx(3.0)
        assert moved["deltas"]["host_build"]["delta_s"] == pytest.approx(0.0)
        assert set(moved["deltas"]) == set(CAUSES)

    def test_empty_when_either_side_lacks_drains(self):
        some = aggregate([attribute_drain({"commit": 0.1})])
        assert attribute_delta({}, some) == {}
        assert attribute_delta(some, {"drains": 0}) == {}
        assert attribute_delta(None, None) == {}


# ---------------------------------------------------------------------------
# device cost model


@pytest.fixture
def fresh_obs():
    OBS.reset()
    OBS.enable(True)
    OBS.enable_cost_model(True)
    yield OBS
    OBS.reset()
    OBS.enable(True)
    OBS.enable_cost_model(True)


class TestCostModelUnits:
    def test_host_estimate_scales_with_cells(self):
        import numpy as np
        a = np.ones((10, 8), np.float32)
        flops, nbytes = host_estimate("run_batch", (a,))
        fpc, bmult = costmodel.KERNEL_COSTS["run_batch"]
        assert flops == pytest.approx(80 * fpc)
        assert nbytes == pytest.approx(a.nbytes * bmult)
        assert host_estimate("no_such_kernel", (a,)) == (0.0, 0.0)

    def test_modeled_seconds_is_the_binding_wall(self):
        pf, pb = costmodel.peaks("cpu")
        # memory-bound shape: bytes wall dominates
        assert modeled_seconds(pf * 0.001, pb * 1.0, "cpu") == \
            pytest.approx(1.0)
        # compute-bound shape: flops wall dominates
        assert modeled_seconds(pf * 2.0, pb * 0.001, "cpu") == \
            pytest.approx(2.0)

    def test_classify_ridge_and_comms(self):
        pf, pb = costmodel.peaks("cpu")
        ridge = pf / pb
        assert classify(ridge * 10.0, 1.0, "cpu") == "compute_bound"
        assert classify(ridge * 0.1, 1.0, "cpu") == "memory_bound"
        # the lane profile overrides intensity entirely
        assert classify(ridge * 10.0, 1.0, "cpu",
                        comms_share=costmodel.COMMS_BOUND_SHARE + 0.01) \
            == "comms_bound"

    def test_record_compile_once_per_plan_key(self):
        import jax
        import jax.numpy as jnp
        fn = jax.jit(lambda x: x * 2 + 1)
        x = jnp.ones((13, 7), jnp.float32)
        cm = CostModel()
        cm.record_compile("run_batch", fn, (x,), {})
        cm.record_compile("run_batch", fn, (x,), {})   # dedup: same key
        rows = cm.kernel_rows("run_batch")
        assert len(rows) == 1
        row = next(iter(rows.values()))
        assert row["source"] in ("xla", "host")
        assert row["flops"] >= 0.0 and row["bytes"] > 0.0
        assert cm.covered() == {"run_batch"}
        cm.reset()
        assert cm.covered() == set()


class TestCostModelEndToEnd:
    def test_fresh_compiles_land_cost_rows(self, fresh_obs):
        """A drain whose executables are freshly minted (cleared jit
        cache) must land cost rows for its kernels: cost_view() carries
        flops/bytes/ai/bound/source per plan, and the /debug/kernels
        snapshot mirrors them with the gate flag."""
        import jax
        jax.clear_caches()     # force delta > 0 → on_compile fires
        api = APIServer()
        _nodes(api, n=12)
        sched = _sched(api)
        api.create_pods(_pods(_specs(48, SEED + 1)))
        assert sched.schedule_pending() == 48

        view = sched.observatory.cost_view()
        assert view, "no cost rows despite fresh compiles"
        for kernel, rows in view.items():
            assert rows
            for row in rows:
                for fld in ("plan", "flops", "bytes", "ai", "modeledMs",
                            "measuredP50Ms", "achievedFraction", "bound",
                            "source"):
                    assert fld in row, (kernel, fld)
                assert row["source"] in ("xla", "host")
                assert row["bound"] in ("compute_bound", "memory_bound",
                                        "comms_bound")
                assert row["flops"] >= 0.0 and row["bytes"] >= 0.0
        snap = sched.observatory.snapshot()
        assert snap["costModelEnabled"] is True
        costed = [k for k, v in snap["kernels"].items() if v["costModel"]]
        assert set(costed) == set(view)


# ---------------------------------------------------------------------------
# verdict stamping end to end + metric families


class TestVerdictEndToEnd:
    def test_drains_carry_critical_path_and_metrics_move(self):
        api = APIServer()
        _nodes(api, n=12)
        sched = _sched(api)
        assert sched.critical_path_enabled    # Beta gate defaults on
        api.create_pods(_pods(_specs(96, SEED + 2)))
        assert sched.schedule_pending() == 96

        cps = _verdicts(sched)
        assert cps, "no drain carried a criticalPath stamp"
        for cp in cps:
            assert cp["verdict"] in CAUSES
            assert set(cp["causes"]) == set(CAUSES)
            # lock-step operation: backpressure is structurally zero
            assert cp["causes"]["backpressure"] == 0.0
            assert cp["chain"], "a committed drain must bind on something"
        m = sched.metrics
        # the verdict counter ticks once per stamped drain
        assert sum(m.bottleneck_drains.value(c) for c in CAUSES) == len(cps)
        assert m.bottleneck_drains.value("backpressure") == 0.0
        # the seconds family sums what the stamps attributed
        for cause in CAUSES:
            want = sum(cp["causes"][cause] for cp in cps)
            assert m.critical_path_seconds.value(cause) == \
                pytest.approx(want, abs=1e-5)
        assert sum(m.critical_path_seconds.value(c) for c in CAUSES) > 0

    def test_gate_off_means_no_stamp_no_movement(self):
        cfg = KubeSchedulerConfiguration(feature_gates={
            "CriticalPathObservatory": False})
        api = APIServer()
        _nodes(api)
        try:
            sched = _sched(api, config=cfg)
            assert not sched.critical_path_enabled
            api.create_pods(_pods(_specs(32, SEED + 3)))
            assert sched.schedule_pending() == 32
            assert _verdicts(sched) == []
            for d in sched.flight.dump():
                assert d["criticalPath"] == {}
            m = sched.metrics
            for cause in CAUSES:
                assert m.critical_path_seconds.value(cause) == 0.0
                assert m.bottleneck_drains.value(cause) == 0.0
        finally:
            # the gate-off ctor disabled the process-global cost model
            OBS.enable_cost_model(True)


# ---------------------------------------------------------------------------
# /debug/criticalpath


class TestDebugEndpoint:
    def test_serves_window_and_aggregate(self):
        api = APIServer()
        _nodes(api, n=12)
        sched = _sched(api)
        api.create_pods(_pods(_specs(96, SEED + 4)))
        assert sched.schedule_pending() == 96
        n_stamped = len(_verdicts(sched))
        assert n_stamped >= 2

        srv = SchedulerServer(sched).start()
        try:
            code, body = _get(srv.port, "/debug/criticalpath")
            assert code == 200
            out = json.loads(body)
            assert len(out["drains"]) == n_stamped
            for row in out["drains"]:
                assert row["criticalPath"]["verdict"] in CAUSES
                assert {"seq", "drainId", "pods", "profile"} <= set(row)
            agg = out["aggregate"]
            assert agg["drains"] == n_stamped
            assert agg["dominant"] in CAUSES
            assert agg["ceiling_factor"] >= 1.0
            # ?limit=N windows the dump to the most recent N
            code, body = _get(srv.port, "/debug/criticalpath?limit=1")
            assert code == 200
            out = json.loads(body)
            assert len(out["drains"]) == 1
            assert out["aggregate"]["drains"] == 1
            # the endpoint advertises itself at the /debug index
            code, body = _get(srv.port, "/debug")
            assert code == 200
            assert "/debug/criticalpath" in body
        finally:
            srv.stop()

    def test_404_with_gate_off(self):
        cfg = KubeSchedulerConfiguration(feature_gates={
            "CriticalPathObservatory": False})
        api = APIServer()
        _nodes(api)
        try:
            sched = _sched(api, config=cfg)
            srv = SchedulerServer(sched).start()
            try:
                code, body = _get(srv.port, "/debug/criticalpath")
                assert code == 404
                assert "CriticalPathObservatory" in body
            finally:
                srv.stop()
        finally:
            OBS.enable_cost_model(True)


# ---------------------------------------------------------------------------
# tools/check.py cost_model_gaps


def _load_check():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_tpu_tools_check", os.path.join(REPO, "tools", "check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCostModelGaps:
    def test_real_config_fully_covered(self):
        assert _load_check().cost_model_gaps() == []

    def test_kernel_without_cost_entry_reported(self, monkeypatch):
        monkeypatch.setitem(obs_mod.ENTRY_KERNELS, "weird_fn",
                            "no_such_kernel")
        gaps = _load_check().cost_model_gaps({"m": ("weird_fn",)})
        assert len(gaps) == 1
        assert "m.weird_fn" in gaps[0] and "no_such_kernel" in gaps[0]
        assert "KERNEL_COSTS" in gaps[0]

    def test_unmapped_entry_left_to_observatory_gaps(self):
        # no ENTRY_KERNELS mapping at all: observatory_gaps owns that
        # finding; cost_model_gaps must not double-report it
        assert _load_check().cost_model_gaps({"m": ("bogus_fn",)}) == []


# ---------------------------------------------------------------------------
# stall attribution under the streaming pipeline (ISSUE 20 satellite)


class TestStallAttribution:
    def _assert_stall_attributed(self, sched, pipe, stage):
        cps = _verdicts(sched)
        assert cps
        attributed = sum(cp["causes"]["backpressure"] for cp in cps)
        total = pipe.backpressure_stall_seconds()
        # conservation: every attributed stall second came off the
        # pipeline's own stall clock (per-drain rounding is 1e-6)
        assert 0.0 < attributed <= total + 1e-4 * len(cps)
        stalls = pipe.stats()["backpressureStallSeconds"]
        assert stalls[stage] > 0.0
        # the stall was real wall, not counter noise: bounded by the
        # wait count times the poll horizon (poll_s * 10 per wait)
        assert stalls[stage] <= \
            pipe._backpressure[stage] * pipe.poll_s * 10 + 1.0
        # the blocked window dominates a sub-ms drain: a backpressure
        # verdict must surface
        assert any(cp["verdict"] == "backpressure" for cp in cps)
        # and the stamps agree with the metric families
        m = sched.metrics
        assert m.pipeline_backpressure.value(stage) >= 1.0
        assert m.critical_path_seconds.value("backpressure") == \
            pytest.approx(attributed, abs=1e-4)
        assert m.bottleneck_drains.value("backpressure") >= 1.0

    def test_ingest_stall_lands_backpressure_verdict(self):
        """Dispatch depth caps ingest: the stalled window must land on a
        committed drain as `backpressure` cause seconds conserved
        against the pipeline's stall clock."""
        api = APIServer()
        _nodes(api)
        sched = _sched(api)
        sched.prime()
        real_commit = sched.commit_ready
        sched.commit_ready = lambda limit=0: 0      # commits stall
        pipe = StreamingPipeline(sched, dispatch_depth=1)
        pipe.start()
        try:
            pipe.feed(_pods(_specs(16, SEED + 5)), close=True)
            blocked = threading.Thread(
                target=pipe.feed,
                args=(_pods(_specs(16, SEED + 6, prefix="q")),),
                kwargs={"close": True})
            blocked.start()
            assert _await(lambda: pipe._backpressure["ingest"] > 0), \
                "ingest never saw backpressure"
            time.sleep(0.05)      # let the stall clock accumulate wall
            sched.commit_ready = real_commit        # commits resume
            blocked.join(timeout=20.0)
            assert not blocked.is_alive()
            pipe.drain(timeout=30.0)
        finally:
            sched.commit_ready = real_commit
            pipe.stop()
        assert not pipe.errors
        self._assert_stall_attributed(sched, pipe, "ingest")

    def test_device_stall_lands_backpressure_verdict(self):
        """Commit backlog caps dispatch: same conservation, other
        direction."""
        api = APIServer()
        _nodes(api)
        sched = _sched(api)
        sched.prime()
        real_flush = sched.dispatcher.flush
        sched.dispatcher.flush = lambda *a, **k: 0  # echo stalls
        pipe = StreamingPipeline(sched, commit_backlog_pods=1)
        pipe.start()
        try:
            pipe.feed(_pods(_specs(16, SEED + 7)), close=True)
            assert _await(lambda: len(sched.dispatcher) > 0), \
                "commit backlog never formed"
            blocked = threading.Thread(
                target=pipe.feed,
                args=(_pods(_specs(16, SEED + 8, prefix="q")),),
                kwargs={"close": True})
            blocked.start()
            assert _await(lambda: pipe._backpressure["device"] > 0), \
                "dispatch never saw commit-backlog backpressure"
            time.sleep(0.05)
            sched.dispatcher.flush = real_flush     # the echo drains
            blocked.join(timeout=20.0)
            assert not blocked.is_alive()
            pipe.drain(timeout=30.0)
        finally:
            sched.dispatcher.flush = real_flush
            pipe.stop()
        assert not pipe.errors
        self._assert_stall_attributed(sched, pipe, "device")

    def test_lockstep_drains_never_say_backpressure(self):
        """No pipeline → no backpressure cause, structurally: the
        attribution reads the pipeline's stall clock, and a lock-step
        scheduler has none."""
        api = APIServer()
        _nodes(api, n=12)
        sched = _sched(api)
        for chunk in range(4):
            api.create_pods(_pods(_specs(32, SEED + 9 + chunk,
                                         prefix=f"c{chunk}-")))
            sched.schedule_pending()
        cps = _verdicts(sched)
        assert len(cps) >= 4
        for cp in cps:
            assert cp["verdict"] != "backpressure"
            assert cp["causes"]["backpressure"] == 0.0
        # host-side causes carry the cycle (the ISSUE 20 acceptance
        # shape: host_build/idle/commit, never a stall)
        agg = aggregate(cps)
        assert agg["dominant"] in set(CAUSES) - {"backpressure"}
        assert sched.metrics.bottleneck_drains.value("backpressure") == 0.0


# ---------------------------------------------------------------------------
# overhead gate (slow tier)


@pytest.mark.slow
class TestCriticalPathOverheadGate:
    def test_overhead_within_5_percent_at_5k_nodes(self):
        """ISSUE 20 acceptance: SchedulingBasic-shaped 5k-node drains
        with CriticalPathObservatory ON (verdicts + cost model) stay
        within 5% of gate-OFF throughput (median of 3 measured passes
        each, warm shapes — the ISSUE 13/14 gate shape)."""

        def _feed_many(api, n, start=0):
            api.create_pods([make_pod(f"p{start + i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj() for i in range(n)])

        def one_pass(gate_on):
            cfg = KubeSchedulerConfiguration(feature_gates={
                "CriticalPathObservatory": gate_on})
            api = APIServer()
            sched = Scheduler(api, batch_size=8192, config=cfg)
            for i in range(5000):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 32, "memory": "64Gi", "pods": 110}).obj())
            sched.prime()
            t0 = time.perf_counter()
            created = 0
            while created < 10000:
                _feed_many(api, 512, start=created)
                created += 512
                sched.schedule_pending(wait=False)
            sched.schedule_pending()
            dt = time.perf_counter() - t0
            assert sched.scheduled_count == created
            return created / dt

        try:
            one_pass(True)   # warm every executable outside the measurement
            off = sorted(one_pass(False) for _ in range(3))[1]
            on = sorted(one_pass(True) for _ in range(3))[1]
        finally:
            OBS.enable(True)
            OBS.enable_cost_model(True)
        assert on >= 0.95 * off, (
            f"critical-path overhead gate: on={on:.0f} off={off:.0f} pods/s "
            f"({on / off - 1:+.1%})")
