"""Preemption (PostFilter) — Evaluator semantics + end-to-end eviction.

Mirrors the reference's TestPostFilter / dry-run behaviors
(pkg/scheduler/framework/preemption/preemption.go:268,431,658;
plugins/defaultpreemption/default_preemption_test.go): victim selection is
minimal, pick ordering follows the 5-step rules, Never policy opts out, and
an end-to-end preemption frees the node, nominates the preemptor, and binds
it on the next cycle.
"""

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.framework.preemption import Candidate, Evaluator
from kubernetes_tpu.framework.types import PodInfo
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _cluster(n_nodes=3, cpu=4, run_min=10**9):
    api = APIServer()
    clock = FakeClock()
    sched = Scheduler(api, batch_size=64, clock=clock)
    sched._clock_handle = clock
    sched.UNIFORM_RUN_MIN = run_min  # host/scan path keeps tests deterministic
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": cpu, "memory": "16Gi", "pods": 110}).obj())
    return api, sched


def _fill(api, sched, n_nodes=3, cpu_each="4", prio=0):
    for i in range(n_nodes):
        api.create_pod(make_pod(f"low{i}").req(
            {"cpu": cpu_each, "memory": "1Gi"}).priority(prio).obj())
    assert sched.schedule_pending() == n_nodes


class TestEndToEnd:
    def test_high_priority_evicts_and_lands(self):
        api, sched = _cluster()
        _fill(api, sched)
        # cluster is full; a high-priority pod must preempt exactly one victim
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        assert sched.schedule_pending() == 0   # this cycle: nominate + evict
        vip = api.pods["default/vip"]
        assert vip.status.nominated_node_name != ""
        assert sched.preemption_attempts == 1
        # exactly one victim deleted
        remaining = [p for p in api.pods.values() if p.name.startswith("low")]
        assert len(remaining) == 2
        # victim delete requeued the preemptor; next cycle binds it onto the
        # freed (nominated) node
        sched._clock_handle.t += 15.0   # past the requeue backoff
        sched.flush_queues()
        bound = sched.schedule_pending()
        assert bound == 1
        assert api.pods["default/vip"].spec.node_name == vip.status.nominated_node_name

    def test_equal_priority_cannot_preempt(self):
        api, sched = _cluster()
        _fill(api, sched, prio=50)
        api.create_pod(make_pod("peer").req({"cpu": "4", "memory": "1Gi"})
                       .priority(50).obj())
        assert sched.schedule_pending() == 0
        assert api.pods["default/peer"].status.nominated_node_name == ""
        assert len([p for p in api.pods.values()
                    if p.name.startswith("low")]) == 3

    def test_preemption_policy_never(self):
        api, sched = _cluster()
        _fill(api, sched)
        pod = make_pod("nice").req({"cpu": "4", "memory": "1Gi"}).priority(100).obj()
        pod.spec.preemption_policy = "Never"
        api.create_pod(pod)
        assert sched.schedule_pending() == 0
        assert api.pods["default/nice"].status.nominated_node_name == ""
        assert len(api.pods) == 4  # nothing deleted

    def test_minimal_victim_set(self):
        # node n0 holds 4×1cpu low pods; preemptor needs 2cpu → exactly two
        # victims (the least important two), the other two reprieved
        api, sched = _cluster(n_nodes=1, cpu=4)
        for i in range(4):
            api.create_pod(make_pod(f"low{i}").req(
                {"cpu": "1", "memory": "1Gi"}).priority(i).obj())
        assert sched.schedule_pending() == 4
        api.create_pod(make_pod("vip").req({"cpu": "2", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        survivors = sorted(p.name for p in api.pods.values()
                           if p.name.startswith("low"))
        # lowest-priority pods (low0, low1) evicted; low2/low3 reprieved
        assert survivors == ["low2", "low3"]
        sched._clock_handle.t += 15.0
        sched.flush_queues()
        assert sched.schedule_pending() == 1
        assert api.pods["default/vip"].spec.node_name == "n0"

    def test_victims_spread_resolution_via_device_path_after(self):
        # after preemption resolves, subsequent pods take the device path
        api, sched = _cluster(n_nodes=2, cpu=4, run_min=16)
        _fill(api, sched, n_nodes=2)
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(10).obj())
        sched.schedule_pending()
        sched._clock_handle.t += 15.0
        sched.flush_queues()
        assert sched.schedule_pending() == 1
        assert not sched.queue.nominator.nominated_pods  # nomination cleared


class TestPickOneNode:
    def _cand(self, node, prios, idx0=0):
        return Candidate(node_name=node, victims=[
            PodInfo.of(make_pod(f"v-{node}-{i}").priority(p).obj())
            for i, p in enumerate(prios)])

    def test_no_victims_wins(self):
        c = Evaluator.pick_one_node([
            self._cand("a", [5]), Candidate(node_name="b"), self._cand("c", [1])])
        assert c.node_name == "b"

    def test_lowest_max_priority_wins(self):
        c = Evaluator.pick_one_node([
            self._cand("a", [9, 1]), self._cand("b", [5, 4]),
            self._cand("c", [8, 2])])
        assert c.node_name == "b"

    def test_smallest_priority_sum_breaks_tie(self):
        c = Evaluator.pick_one_node([
            self._cand("a", [5, 5]), self._cand("b", [5, 3])])
        assert c.node_name == "b"

    def test_fewest_victims_breaks_tie(self):
        c = Evaluator.pick_one_node([
            self._cand("a", [5, 3, 0]), self._cand("b", [5, 3])])
        assert c.node_name == "b"


class TestNominatedPods:
    def test_nominated_resources_block_other_pods(self):
        """A pending preemptor's nominated resources must repel lower-pri
        pods (RunFilterPluginsWithNominatedPods two-pass,
        runtime/framework.go:1158)."""
        api, sched = _cluster(n_nodes=1, cpu=4)
        _fill(api, sched, n_nodes=1)
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()          # evict + nominate, not yet rebound
        # a new low-priority pod arrives while the nomination is pending;
        # it must NOT steal the freed capacity
        api.create_pod(make_pod("sneak").req({"cpu": "4", "memory": "1Gi"})
                       .priority(0).obj())
        sched._clock_handle.t += 15.0
        sched.flush_queues()
        sched.schedule_pending()
        assert api.pods["default/vip"].spec.node_name == "n0"
        assert api.pods["default/sneak"].spec.node_name == ""


class TestPDB:
    """PDB-aware victim selection (preemption.go:658 step 1 +
    filterPodsWithPDBViolation; default_preemption.go:640 reprieve order)."""

    def _pdb(self, name, labels, min_available=None, max_unavailable=None):
        from kubernetes_tpu.api.types import (LabelSelector, ObjectMeta,
                                              PodDisruptionBudget)
        return PodDisruptionBudget(
            metadata=ObjectMeta(name=name),
            selector=LabelSelector.of(match_labels=labels),
            min_available=min_available, max_unavailable=max_unavailable)

    def test_disruptions_allowed_status(self):
        api = APIServer()
        api.create_node(make_node("n0").capacity(
            {"cpu": 16, "memory": "32Gi", "pods": 10}).obj())
        for i in range(4):
            p = make_pod(f"a{i}").label("app", "a").obj()
            api.create_pod(p)
            api.bind(p, "n0")
        api.create_pdb(self._pdb("pdb-min", {"app": "a"}, min_available=3))
        api.create_pdb(self._pdb("pdb-max", {"app": "a"}, max_unavailable=1))
        api.create_pdb(self._pdb("pdb-pct", {"app": "a"}, min_available="50%"))
        allowed = {p.name: p.disruptions_allowed for p in api.list_pdbs()}
        assert allowed == {"pdb-min": 1, "pdb-max": 1, "pdb-pct": 2}

    def test_violation_partition_consumes_budget(self):
        from kubernetes_tpu.framework.types import PodInfo
        pdb = self._pdb("pdb", {"app": "a"}, min_available=1)
        pdb.disruptions_allowed = 1
        pods = [PodInfo.of(make_pod(f"p{i}").label("app", "a").obj())
                for i in range(3)]
        violating, ok = Evaluator._filter_pods_with_pdb_violation(pods, [pdb])
        # budget 1: first pod consumes it, the rest violate
        assert [pi.pod.name for pi in ok] == ["p0"]
        assert [pi.pod.name for pi in violating] == ["p1", "p2"]

    def test_pdb_changes_picked_node(self):
        """Two identical candidates; the victim on n0 is PDB-protected
        (0 allowed disruptions) → pick prefers n1 (fewest violations)."""
        api, sched = _cluster(n_nodes=2, cpu=4)
        api.create_pod(make_pod("guarded").req({"cpu": "4", "memory": "1Gi"})
                       .label("app", "guarded").node("n0").obj())
        api.create_pod(make_pod("plain").req({"cpu": "4", "memory": "1Gi"})
                       .label("app", "plain").node("n1").obj())
        api.create_pdb(self._pdb("pdb", {"app": "guarded"}, min_available=1))
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        assert api.pods["default/vip"].status.nominated_node_name == "n1"
        assert "default/plain" not in api.pods       # plain evicted
        assert "default/guarded" in api.pods         # guarded survived

    def test_pdb_violated_when_no_alternative(self):
        """With every victim PDB-protected, preemption still proceeds
        (PDBs are best-effort in preemption, preemption.go:640)."""
        api, sched = _cluster(n_nodes=1, cpu=4)
        api.create_pod(make_pod("guarded").req({"cpu": "4", "memory": "1Gi"})
                       .label("app", "g").node("n0").obj())
        api.create_pdb(self._pdb("pdb", {"app": "g"}, min_available=1))
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        assert api.pods["default/vip"].status.nominated_node_name == "n0"
        assert "default/guarded" not in api.pods


class TestDeviceOverlayUnderNomination:
    """Nominated pods as a fit-only device overlay (VERDICT r4 #4): the
    batch path keeps running while a nomination is pending, and the
    nominated capacity repels lower-priority pods exactly like the host
    two-pass (runtime/framework.go:1158)."""

    def test_device_path_stays_active_and_respects_overlay(self):
        api, sched = _cluster(n_nodes=3, cpu=4)
        _fill(api, sched, n_nodes=3)    # cluster full
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()        # nominate + evict one victim
        nominated_node = api.pods["default/vip"].status.nominated_node_name
        assert nominated_node
        # victim delete freed the nominated node's capacity; a flood of
        # low-priority pods arrives while the nomination is pending
        before = sched.device_batches
        for i in range(4):
            api.create_pod(make_pod(f"flood{i}")
                           .req({"cpu": "4", "memory": "1Gi"}).obj())
        sched.schedule_pending()
        # device path (overlay) served the flood — no host fallback
        assert sched.device_batches > before
        # none of them stole the nominated capacity
        for i in range(4):
            assert api.pods[f"default/flood{i}"].spec.node_name == "", i
        # and the preemptor still lands on its nominated node
        sched._clock_handle.t += 15.0
        sched.flush_queues()
        sched.schedule_pending()
        assert api.pods["default/vip"].spec.node_name == nominated_node

    def test_overlay_matches_host_oracle_decisions(self):
        """Same churn with the overlay path vs forced host path → same
        binds."""
        def run(force_host):
            api, sched = _cluster(n_nodes=4, cpu=4)
            if force_host:
                sched._overlay_eligible = lambda qpis: False
            _fill(api, sched, n_nodes=4)
            api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                           .priority(100).obj())
            sched.schedule_pending()
            for i in range(6):
                api.create_pod(make_pod(f"w{i}")
                               .req({"cpu": "2", "memory": "1Gi"}).obj())
            sched.schedule_pending()
            sched._clock_handle.t += 15.0
            sched.flush_queues()
            sched.schedule_pending()
            return {uid: p.spec.node_name for uid, p in api.pods.items()}

        assert run(False) == run(True)

    def test_lower_priority_nominated_pod_forces_host_path(self):
        """A nominated pod that does NOT outrank the drain cannot be
        modeled by the overlay (the reference only adds higher-or-equal
        priority nominated pods) — the drain must fall back."""
        api, sched = _cluster(n_nodes=2, cpu=4)
        _fill(api, sched, n_nodes=2, prio=0)
        api.create_pod(make_pod("mid").req({"cpu": "4", "memory": "1Gi"})
                       .priority(10).obj())
        sched.schedule_pending()        # nominates at priority 10
        qpi_like = [type("Q", (), {"pod": make_pod("hi").priority(100).obj()})]
        assert not sched._overlay_eligible(qpi_like)


class TestExtenderPreemptVerb:
    """extender.go ProcessPreemption (:107-110) + preemption.go:316
    callExtenders: a preemption-capable extender vetoes candidates."""

    def test_extender_veto_changes_picked_node(self):
        from kubernetes_tpu.framework.extender import CallableExtender
        from kubernetes_tpu.scheduler import Profile, Scheduler
        from kubernetes_tpu.scheduler import default_plugins
        from kubernetes_tpu.framework.runtime import Framework

        api = APIServer()
        clock = FakeClock()
        ext = CallableExtender(
            name="veto-n0",
            preempt_fn=lambda pod, victims: {
                n: v for n, v in victims.items() if n != "n0"})
        fwk = Framework("default-scheduler", default_plugins(api))
        prof = Profile(framework=fwk, extenders=(ext,))
        sched = Scheduler(api, profiles=[prof], batch_size=64, clock=clock)
        for i in range(2):
            api.create_node(make_node(f"n{i}").capacity(
                {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        for i in range(2):
            p = make_pod(f"low{i}").req({"cpu": "4", "memory": "1Gi"}).obj()
            api.create_pod(p)
            api.bind(p, f"n{i}")
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        # without the extender both nodes tie and n0 (first) wins; the
        # preempt verb vetoes n0, so n1 must be nominated
        assert api.pods["default/vip"].status.nominated_node_name == "n1"
        assert "default/low1" not in api.pods
        assert "default/low0" in api.pods

    def test_extender_total_veto_blocks_preemption(self):
        from kubernetes_tpu.framework.extender import CallableExtender
        from kubernetes_tpu.framework.runtime import Framework
        from kubernetes_tpu.scheduler import Profile, Scheduler, default_plugins

        api = APIServer()
        ext = CallableExtender(name="veto-all",
                               preempt_fn=lambda pod, victims: {})
        fwk = Framework("default-scheduler", default_plugins(api))
        prof = Profile(framework=fwk, extenders=(ext,))
        sched = Scheduler(api, profiles=[prof], batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        p = make_pod("low").req({"cpu": "4", "memory": "1Gi"}).obj()
        api.create_pod(p)
        api.bind(p, "n0")
        api.create_pod(make_pod("vip").req({"cpu": "4", "memory": "1Gi"})
                       .priority(100).obj())
        sched.schedule_pending()
        assert api.pods["default/vip"].status.nominated_node_name == ""
        assert "default/low" in api.pods
