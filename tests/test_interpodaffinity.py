"""InterPodAffinity parity tests (modeled on reference
pkg/scheduler/framework/plugins/interpodaffinity/filtering_test.go and
scoring_test.go canonical cases)."""

from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.types import NodeInfo, PodInfo
from kubernetes_tpu.plugins.interpodaffinity import (
    InterPodAffinity, InterPodAffinityArgs, NamespaceLister)
from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def mk_cluster():
    nodes = {}
    for name, zone in (("node-a", "zoneA"), ("node-b", "zoneA"),
                       ("node-x", "zoneB"), ("node-y", "zoneB")):
        n = make_node(name).zone(zone).label(HOST, name).obj()
        nodes[name] = NodeInfo(node=n)
    return nodes


def place(nodes, node_name, pod):
    nodes[node_name].add_pod(PodInfo.of(pod))


def run_filter(plugin, pod, nodes):
    state = CycleState()
    nis = list(nodes.values())
    _, status = plugin.pre_filter(state, pod, nis)
    if status.is_skip():
        return {ni.name: status for ni in nis}, state, True
    return {ni.name: plugin.filter(state, pod, ni) for ni in nis}, state, False


class TestFilter:
    def test_required_affinity_zone(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("store").label("app", "store").obj())
        pod = make_pod("incoming").pod_affinity(ZONE, {"app": "store"}).obj()
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert statuses["node-a"].is_success()
        assert statuses["node-b"].is_success()  # same zone
        assert statuses["node-x"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert statuses["node-y"].code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_first_pod_escape_hatch(self):
        # pod has affinity matching itself and no pod in the cluster matches
        # → allowed everywhere (filtering.go:381-397).
        nodes = mk_cluster()
        pod = (make_pod("incoming").label("app", "store")
               .pod_affinity(ZONE, {"app": "store"}).obj())
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert all(s.is_success() for s in statuses.values())

    def test_first_pod_no_self_match_stays_pending(self):
        nodes = mk_cluster()
        pod = make_pod("incoming").pod_affinity(ZONE, {"app": "store"}).obj()
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert all(not s.is_success() for s in statuses.values())

    def test_incoming_anti_affinity_hostname(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("web1").label("app", "web").obj())
        pod = make_pod("incoming").pod_affinity(HOST, {"app": "web"}, anti=True).obj()
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert statuses["node-a"].code == Code.UNSCHEDULABLE
        for n in ("node-b", "node-x", "node-y"):
            assert statuses[n].is_success()

    def test_existing_pods_anti_affinity(self):
        nodes = mk_cluster()
        # existing pod on node-a anti-affines (zone) to app=web pods
        existing = (make_pod("guard").label("app", "guard")
                    .pod_affinity(ZONE, {"app": "web"}, anti=True).obj())
        place(nodes, "node-a", existing)
        pod = make_pod("incoming").label("app", "web").obj()
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert statuses["node-a"].code == Code.UNSCHEDULABLE
        assert statuses["node-b"].code == Code.UNSCHEDULABLE  # same zone
        assert statuses["node-x"].is_success()
        assert statuses["node-y"].is_success()

    def test_skip_when_nothing_relevant(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("p").label("app", "x").obj())
        pod = make_pod("incoming").obj()
        _, _, skipped = run_filter(InterPodAffinity(), pod, nodes)
        assert skipped

    def test_namespace_scoping(self):
        nodes = mk_cluster()
        # store pod lives in ns "other"; incoming pod in "default" with a
        # term that has no explicit namespaces → scoped to default → no match.
        place(nodes, "node-a",
              make_pod("store", namespace="other").label("app", "store").obj())
        pod = make_pod("incoming").pod_affinity(ZONE, {"app": "store"}).obj()
        statuses, _, _ = run_filter(InterPodAffinity(), pod, nodes)
        assert all(not s.is_success() for s in statuses.values())
        # explicit namespaces=("other",) → matches zoneA
        pod2 = make_pod("incoming2").pod_affinity(
            ZONE, {"app": "store"}, namespaces=("other",)).obj()
        statuses2, _, _ = run_filter(InterPodAffinity(), pod2, nodes)
        assert statuses2["node-a"].is_success()
        assert not statuses2["node-x"].is_success()

    def test_namespace_selector(self):
        nodes = mk_cluster()
        place(nodes, "node-a",
              make_pod("store", namespace="team-a").label("app", "store").obj())
        pod = make_pod("incoming").pod_affinity(ZONE, {"app": "store"}).obj()
        # rewrite the term with a namespaceSelector matching team=a
        aff = pod.spec.affinity
        import dataclasses
        term = dataclasses.replace(aff.pod_affinity.required[0],
                                   namespace_selector=LabelSelector.of({"team": "a"}))
        pod.spec.affinity = dataclasses.replace(
            aff, pod_affinity=dataclasses.replace(aff.pod_affinity, required=(term,)))
        ns_lister = NamespaceLister({"team-a": {"team": "a"}, "default": {}})
        statuses, _, _ = run_filter(InterPodAffinity(ns_lister=ns_lister), pod, nodes)
        assert statuses["node-a"].is_success()
        assert not statuses["node-x"].is_success()

    def test_add_remove_pod_extensions(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("web1").label("app", "web").obj())
        pod = make_pod("incoming").pod_affinity(HOST, {"app": "web"}, anti=True).obj()
        pl = InterPodAffinity()
        state = CycleState()
        pl.pre_filter(state, pod, list(nodes.values()))
        assert not pl.filter(state, pod, nodes["node-a"]).is_success()
        victim = nodes["node-a"].pods[0]
        pl.remove_pod(state, pod, victim, nodes["node-a"])
        assert pl.filter(state, pod, nodes["node-a"]).is_success()
        pl.add_pod(state, pod, victim, nodes["node-a"])
        assert not pl.filter(state, pod, nodes["node-a"]).is_success()


class TestScore:
    def run(self, pod, nodes, args=None):
        pl = InterPodAffinity(args=args)
        state = CycleState()
        nis = list(nodes.values())
        status = pl.pre_score(state, pod, nis)
        if status.is_skip():
            return None
        scores = []
        for ni in nis:
            s, st = pl.score(state, pod, ni)
            assert st.is_success()
            scores.append(s)
        pl.normalize_scores(state, pod, scores)
        return dict(zip(nodes.keys(), scores))

    def test_preferred_affinity(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("store").label("app", "store").obj())
        pod = (make_pod("incoming")
               .preferred_pod_affinity(ZONE, {"app": "store"}, weight=5).obj())
        scores = self.run(pod, nodes)
        assert scores["node-a"] == scores["node-b"] == 100
        assert scores["node-x"] == scores["node-y"] == 0

    def test_preferred_anti_affinity(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("noisy").label("app", "noisy").obj())
        pod = (make_pod("incoming")
               .preferred_pod_affinity(ZONE, {"app": "noisy"}, weight=5, anti=True).obj())
        scores = self.run(pod, nodes)
        assert scores["node-x"] == scores["node-y"] == 100
        assert scores["node-a"] == scores["node-b"] == 0

    def test_symmetric_preferred_of_existing(self):
        # existing pod prefers app=web neighbors; incoming pod has app=web
        # and no terms of its own → symmetric credit.
        nodes = mk_cluster()
        existing = (make_pod("social").label("app", "social")
                    .preferred_pod_affinity(ZONE, {"app": "web"}, weight=3).obj())
        place(nodes, "node-a", existing)
        pod = make_pod("incoming").label("app", "web").obj()
        scores = self.run(pod, nodes)
        assert scores["node-a"] == scores["node-b"] == 100
        assert scores["node-x"] == 0

    def test_hard_affinity_weight_symmetry(self):
        # existing pod on node-a REQUIRES app=web neighbors; with
        # HardPodAffinityWeight>0 incoming app=web pods get credit there.
        nodes = mk_cluster()
        existing = (make_pod("needy").label("app", "needy")
                    .pod_affinity(ZONE, {"app": "web"}).obj())
        place(nodes, "node-a", existing)
        pod = make_pod("incoming").label("app", "web").obj()
        scores = self.run(pod, nodes, args=InterPodAffinityArgs(hard_pod_affinity_weight=10))
        assert scores["node-a"] == 100
        assert scores["node-x"] == 0

    def test_skip_when_no_terms_anywhere(self):
        nodes = mk_cluster()
        place(nodes, "node-a", make_pod("plain").label("app", "x").obj())
        pod = make_pod("incoming").obj()
        assert self.run(pod, nodes) is None
