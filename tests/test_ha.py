"""Active/standby HA (ISSUE 12): lease-fenced failover with a hot spare.

Four gates this file establishes:

- the lease state machine (ha/lease.py): acquire → renew → depose →
  re-elect, the deposed-leader slow path (step down at the renew
  DEADLINE, before the lease expires), and the backoff-gated acquire
  retry — all against the API server's lease verbs;
- the fencing proof (ha/fencing.py + backend/dispatcher.py): a deposed
  leader's delayed flush carries its STALE generation and is rejected
  server-side (`fenced_writes_rejected_total` > 0), the unwind forgets
  every assumed pod, and the successor binds the affected pods exactly
  once (zero double-binds);
- warm-standby state parity (ha/standby.py): after N audited drains a
  synced standby's device staging arrays BIT-MATCH a fresh scheduler's
  tensorize of the same store;
- the kill-at-every-phase failover soak (slow): the leader dies at
  host_build / device / commit / mid-flush, the spare takes over, and
  the final assignment map is IDENTICAL to an unkilled run — with zero
  double-binds, zero shadow-oracle divergence at 100% sampling, and the
  drain-ledger hash chain intact across the spliced handoff.

Lease chaos (testing/chaos.py): expired-lease storms, mid-renew steals,
renew latency spikes and the clock-skew knob run the electors through
the races a real coordination API exposes, seeded (CHAOS_SEED=N).
"""

import os
import random

import numpy as np
import pytest

from kubernetes_tpu.backend.apiserver import (APIServer, FencedWrite,
                                              LEASE_NAME)
from kubernetes_tpu.ha import (LeaderElector, StandbyScheduler,
                               fence_dispatcher)
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.chaos import ChaosAPIServer, ChaosConfig
from kubernetes_tpu.testing.wrappers import make_node, make_pod

SEED = int(os.environ.get("CHAOS_SEED", "7"))


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class Killed(Exception):
    """Simulated process death: propagates out of the scheduling loop,
    leaving whatever the 'process' had not committed uncommitted."""


def _no_sleep(sched):
    sched.dispatcher.sleep = lambda _s: None
    return sched


def _nodes(api, n=6, cpu=16, mem="32Gi"):
    for i in range(n):
        api.create_node(make_node(f"n{i}")
                        .capacity({"cpu": cpu, "memory": mem, "pods": 80})
                        .zone(f"z{i % 3}").obj())


def _pod_specs(n, seed, prefix="p"):
    rng = random.Random(seed)
    return [(f"{prefix}{i}", 250 * rng.randint(1, 6), 512 * rng.randint(1, 4))
            for i in range(n)]


def _create(api, specs):
    for name, cpu, mem in specs:
        api.create_pod(make_pod(name)
                       .req({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}).obj())


def _assignments(api):
    return {uid: p.spec.node_name for uid, p in api.pods.items()}


def _drive_to_quiescence(api, sched, clock, want_bound, max_rounds=60):
    for _ in range(max_rounds):
        sched.schedule_pending()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if bound >= want_bound:
            return
        clock.t += 10.0
        sched.flush_queues()
    raise AssertionError(
        f"did not quiesce: "
        f"{sum(1 for p in api.pods.values() if p.spec.node_name)}"
        f"/{want_bound} bound, pending={sched.pending_summary()}")


def _audited(sched):
    """Force the shadow audit onto every drain, replayed inline (the
    ledger must see every drain for the tail/handoff assertions)."""
    assert sched.audit is not None, "ShadowOracleAudit gate must be on"
    sched.audit.sample_rate = 1.0
    sched.audit.synchronous = True
    return sched


def _standby(api, clock, ledger=None, identity="sched-b", **kw):
    inner = _audited(_no_sleep(Scheduler(api, batch_size=32, clock=clock,
                                         **kw)))
    return StandbyScheduler(api, identity=identity, ledger=ledger,
                            clock=clock, scheduler=inner)


# -- lease state machine -------------------------------------------------------


def test_lease_acquire_renew_depose_reelect():
    """The full state machine: fresh acquire mints generation 1; renews
    keep it; a dead leader's expiry hands the lease (and generation 2)
    to the next candidate; the deposed leader notices via Conflict but
    KEEPS its stale cached fence token."""
    api = APIServer()
    clock = Clock()
    events = []
    a = LeaderElector(api, "sched-a", clock=clock,
                      on_started_leading=lambda: events.append("a-start"),
                      on_stopped_leading=lambda: events.append("a-stop"))
    b = LeaderElector(api, "sched-b", clock=clock,
                      on_started_leading=lambda: events.append("b-start"),
                      on_stopped_leading=lambda: events.append("b-stop"))

    assert a.tick() is True and a.fence_token() == 1
    assert b.tick() is False and b.fence_token() is None
    clock.t = 10.0
    assert a.tick() is True          # renew: same holder, same generation
    assert a.fence_token() == 1
    assert api.get_lease(LEASE_NAME).lease_transitions == 0

    clock.t = 40.0                   # a stops renewing (dead)
    assert b.tick() is True          # expired lease → b acquires
    assert b.fence_token() == 2
    lease = api.get_lease(LEASE_NAME)
    assert lease.holder_identity == "sched-b"
    assert lease.lease_transitions == 1
    # the deposed leader's next tick observes the loss — but its cached
    # token stays STALE (the fencing contract: late flushes must carry it)
    assert a.tick() is False
    assert not a.is_leader()
    assert a.fence_token() == 1
    assert events == ["a-start", "b-start", "a-stop"]

    # voluntary release hands off without waiting for expiry
    b.release()
    assert not b.is_leader()
    clock.t = 45.0                   # past a's post-conflict backoff gate
    assert a.tick() is True
    assert a.fence_token() == 3      # every holder change bumps it
    assert events[-1] == "a-stop" or events[-2:] == ["b-stop", "a-start"]


def test_deposed_leader_steps_down_before_lease_expiry():
    """client-go's RenewDeadline < LeaseDuration slow path: when renews
    fail transiently, the leader steps down at the renew deadline (10s)
    — while its lease (15s) is still valid in the store — so a
    successor can never overlap a half-dead leader."""
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED, error_rates={"lease_renew": 1.0}))
    stops = []
    a = LeaderElector(chaos, "sched-a", clock=clock,
                      on_stopped_leading=lambda: stops.append(clock.t))
    assert a.tick() is True          # the first acquire is not a renew

    for t in (2.0, 4.0, 6.0, 8.0):
        clock.t = t
        assert a.tick() is True      # transient renew failures: hold on
        assert a.is_leader()
    clock.t = 10.0                   # the renew deadline (15 * 2/3)
    assert a.tick() is False
    assert not a.is_leader()
    assert stops == [10.0]
    # the slow path fired BEFORE lease expiry: the store still shows a
    # valid, unexpired lease held by the stepped-down leader
    lease = chaos.get_lease(LEASE_NAME)
    assert lease.holder_identity == "sched-a"
    assert clock.t - lease.renew_time < lease.lease_duration_s
    assert chaos.injected_errors["lease_renew"] >= 5


def test_nonleader_acquire_backoff_gates_retries():
    """A candidate that lost the race backs off (jittered retry_period)
    instead of hammering the lease on every tick."""
    api = APIServer()
    clock = Clock()
    a = LeaderElector(api, "sched-a", clock=clock)
    b = LeaderElector(api, "sched-b", clock=clock)
    assert a.tick() is True
    assert b.tick() is False
    gate = b._next_acquire
    assert clock.t < gate            # a backoff window was armed
    clock.t = gate / 2
    before = api.get_lease(LEASE_NAME).renew_time
    assert b.tick() is False         # gated: no API call at all
    assert api.get_lease(LEASE_NAME).renew_time == before


# -- fencing -------------------------------------------------------------------


def test_fence_token_rejection_at_api_server():
    """API-server-level contract: a write stamped with a generation
    older than the lease's current one raises FencedWrite; None passes
    (unfenced legacy clients)."""
    api = APIServer()
    _nodes(api, n=2)
    api.acquire_lease(LEASE_NAME, "sched-a", 0.0)       # generation 1
    pod = api.create_pod(make_pod("f0").req({"cpu": "100m"}).obj())
    api.acquire_lease(LEASE_NAME, "sched-b", 20.0)      # generation 2
    with pytest.raises(FencedWrite):
        api.bind(pod, "n0", fence_token=1)
    assert api.fenced_rejections == 1
    assert not api.pods[pod.uid].spec.node_name
    api.bind(pod, "n0", fence_token=2)                  # current token: ok
    api.patch_pod_status(pod, {"type": "PodScheduled"}, fence_token=None)
    assert api.pods[pod.uid].spec.node_name == "n0"


def test_deposed_leader_delayed_flush_is_fenced_and_unwinds():
    """The fencing proof: a leader assumes pods and enqueues their binds
    (stamped with generation 1), dies before flushing; the standby takes
    over (generation 2); the dead leader's delayed flush is rejected
    wholesale, the unwind forgets every assumed pod, and the successor
    binds them — each exactly once."""
    api = APIServer()
    _nodes(api)
    clock = Clock()
    leader = _audited(_no_sleep(Scheduler(api, batch_size=32, clock=clock)))
    el_a = LeaderElector(api, "sched-a", clock=clock,
                         metrics=leader.metrics)
    fence_dispatcher(leader.dispatcher, el_a)
    assert el_a.tick() is True
    leader.prime()

    _create(api, _pod_specs(12, seed=100, prefix="w"))
    # assume + enqueue WITHOUT flushing: drain the queue by hand — this
    # is the instant a real process dies between commit and flush
    qpis = leader.queue.drain(32)
    leader._schedule_batch(qpis)
    leader._drain_pending()
    assert len(leader.dispatcher) > 0
    assert leader.cache.assumed_pods

    standby = _standby(api, clock, ledger=leader.audit.ledger)
    clock.t = 20.0                   # the dead leader's lease expires
    assert standby.tick() is True
    assert standby.scheduler.ha_role == "active"
    assert standby.elector.fence_token() == 2

    # the zombie wakes up and flushes: every bind carries generation 1
    leader.dispatcher.flush()
    assert leader.dispatcher.fenced > 0
    assert api.fenced_rejections > 0
    assert leader.metrics.fenced_writes_rejected.value() > 0
    assert not leader.cache.assumed_pods           # the unwind forgot them
    assert all(not p.spec.node_name for p in api.pods.values())

    # the successor now binds the (still unbound) pods — exactly once
    _drive_to_quiescence(api, standby.scheduler, clock, want_bound=12)
    assert api.binding_count == 12
    m = standby.scheduler.metrics
    assert m.leader_transitions.value("acquired") == 1
    assert standby.scheduler.audit.ledger.verify()


# -- lease chaos ---------------------------------------------------------------


def test_chaos_expired_lease_storms_and_steals():
    """Seeded lease chaos: expirations yank the lease from under the
    holder, mid-renew steals force the Conflict path, and the system
    still converges to exactly one leader with a monotonically bumped
    generation once the storm stops."""
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED, lease_expire_rate=0.3, lease_steal_rate=0.3))
    a = LeaderElector(chaos, "sched-a", clock=clock)
    b = LeaderElector(chaos, "sched-b", clock=clock)
    max_gen = 0
    for _ in range(200):
        clock.t += 2.0
        a.tick()
        b.tick()
        lease = chaos.get_lease(LEASE_NAME)
        if lease is not None:
            assert lease.generation >= max_gen      # fence tokens: monotonic
            max_gen = lease.generation
        for el in (a, b):
            if el.is_leader():
                # a CURRENT leader's cached token matches the store (only
                # deposed leaders go stale — that is the fencing contract)
                assert el.fence_token() <= lease.generation
    assert chaos.lease_expirations > 0
    assert chaos.lease_steals > 0
    # storm over: a stolen lease's thief never renews, so after expiry
    # the real candidates recover to exactly one leader
    chaos.cfg.lease_expire_rate = chaos.cfg.lease_steal_rate = 0.0
    clock.t += 20.0
    for _ in range(8):
        clock.t += 2.0
        a.tick()
        b.tick()
    assert sum(1 for el in (a, b) if el.is_leader()) == 1
    leader = a if a.is_leader() else b
    assert leader.fence_token() == chaos.get_lease(LEASE_NAME).generation


def test_chaos_renew_latency_spike_deposes_leader():
    """A renew that takes longer than the lease duration (injected via a
    clock-wired sleep) leaves the stored renewTime stale: the next
    candidate sees an expired lease and takes over; the laggard's next
    renew hits Conflict and it steps down."""
    clock = Clock()

    def skew_sleep(s):
        clock.t += s

    chaos = ChaosAPIServer(config=ChaosConfig(
        seed=SEED, renew_latency_rate=1.0,
        renew_latency_seconds=(16.0, 16.0)), sleep=skew_sleep)
    a = LeaderElector(chaos, "sched-a", clock=clock)
    b = LeaderElector(chaos, "sched-b", clock=clock)
    assert a.tick() is True          # acquire: no renew spike yet
    clock.t = 2.0
    a.tick()                         # renew stalls 16s inside the call
    assert clock.t >= 18.0
    assert chaos.renew_latency_spikes == 1
    assert b.tick() is True          # renewTime=2, now=18: expired
    assert a.tick() is False         # Conflict → deposed
    assert not a.is_leader() and b.is_leader()
    assert a.fence_token() == 1 and b.fence_token() == 2


def test_chaos_clock_skew_expires_leases_early():
    """The clock-skew knob: a holder whose clock LAGS (skew < -duration)
    records renewTimes in the past, so candidates — reading true time —
    see the lease expire out from under a leader that believes it just
    renewed. The two-clocks failure leases exist to tolerate; the
    takeover still bumps the generation so fencing holds."""
    clock = Clock()
    chaos = ChaosAPIServer(config=ChaosConfig(seed=SEED,
                                              clock_skew_s=-16.0))
    a = LeaderElector(chaos, "sched-a", clock=clock)
    b = LeaderElector(chaos, "sched-b", clock=clock)
    assert a.tick() is True          # fresh acquire: true clock
    clock.t = 2.0
    assert a.tick() is True          # renew recorded at 2 - 16 = -14
    clock.t = 2.5
    assert b.tick() is True          # 2.5 - (-14) > 15: looks expired
    assert b.fence_token() == 2      # the bump still fences a's writes
    assert a.tick() is False         # Conflict: a finds out
    assert not a.is_leader() and b.is_leader()


# -- warm standby --------------------------------------------------------------


def test_standby_warm_state_parity_after_drains():
    """The hot-spare contract: after N audited drains, a synced
    standby's device staging arrays BIT-MATCH a fresh scheduler's
    tensorize of the same store — takeover pays neither the LIST nor
    the tensorize it already did while passive."""
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    leader = _audited(_no_sleep(Scheduler(api, batch_size=32, clock=clock)))
    el_a = LeaderElector(api, "sched-a", clock=clock)
    fence_dispatcher(leader.dispatcher, el_a)
    assert el_a.tick() is True
    leader.prime()

    standby = _standby(api, clock, ledger=leader.audit.ledger)
    assert standby.tick() is False   # the leader renews; spare stays warm
    for wave in range(3):            # N drains land through the leader
        _create(api, _pod_specs(16, seed=100 + wave, prefix=f"w{wave}-"))
        leader.schedule_pending()
        el_a.tick()
        standby.sync()
    assert standby.drains_seen >= 3
    assert standby.ledger.lag(standby.cursor) == 0
    assert standby.last_hash == leader.audit.ledger.head_hash()
    assert standby.scheduler.ha_role == "standby"
    assert standby.scheduler.schedule_pending() == 0   # standbys never write

    fresh = Scheduler(api, batch_size=32, clock=clock)
    fresh.prime()
    warm = standby.scheduler.state
    assert warm.node_index == fresh.state.node_index
    for name, ours, theirs in zip(warm.arrays._fields,
                                  warm.ensure_arrays(),
                                  fresh.state.ensure_arrays()):
        assert np.array_equal(np.asarray(ours), np.asarray(theirs)), \
            f"standby staging array {name!r} diverged from fresh tensorize"


def test_debug_ha_endpoint():
    """/debug/ha serves the standby's full HA view (role, lease, fence
    token, ledger cursor/lag, takeovers)."""
    import json
    import urllib.request

    from kubernetes_tpu.server import SchedulerServer
    api = APIServer()
    _nodes(api, n=2)
    clock = Clock()
    leader = _audited(_no_sleep(Scheduler(api, batch_size=16, clock=clock)))
    el_a = LeaderElector(api, "sched-a", clock=clock)
    fence_dispatcher(leader.dispatcher, el_a)
    assert el_a.tick() is True
    standby = _standby(api, clock, ledger=leader.audit.ledger)
    standby.tick()
    standby.sync()
    srv = SchedulerServer(standby.scheduler, ha=standby).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/ha", timeout=10) as r:
            payload = json.loads(r.read().decode())
    finally:
        srv.stop()
    assert payload["role"] == "standby" and payload["leader"] is False
    assert payload["lease"]["holder"] == "sched-a"
    assert payload["lease"]["generation"] == 1
    assert payload["ledgerLag"] == 0 and payload["takeovers"] == 0


def test_gate_off_fallback_matrix():
    """With `ActiveStandbyHA` off the elector still works, but the
    dispatcher goes unfenced, sync() is a no-op (no ledger tail, no
    device pre-warm) and takeover skips the splice — a cold resync, the
    pre-ISSUE-12 posture."""
    api = APIServer()
    _nodes(api, n=2)
    clock = Clock()
    leader = _audited(_no_sleep(Scheduler(api, batch_size=16, clock=clock)))
    el_a = LeaderElector(api, "sched-a", clock=clock)
    fence_dispatcher(leader.dispatcher, el_a)
    assert el_a.tick() is True
    leader.prime()
    _create(api, _pod_specs(6, seed=9))
    leader.schedule_pending()
    leader.audit.flush()
    assert leader.audit.ledger.cursor() > 0

    inner = _audited(_no_sleep(Scheduler(
        api, batch_size=16, clock=clock,
        config=KubeSchedulerConfiguration(
            feature_gates={"ActiveStandbyHA": False}))))
    standby = StandbyScheduler(api, identity="sched-b",
                               ledger=leader.audit.ledger,
                               clock=clock, scheduler=inner)
    assert standby.enabled is False
    # elector still works; writes are simply unfenced
    assert standby.tick() is False
    assert standby.scheduler.dispatcher.fence is None
    # sync() is a no-op: nothing consumed, cursor never advances
    assert standby.sync() == 0
    assert standby.cursor == 0 and standby.drains_seen == 0
    # takeover is a cold resync with no splice: this instance's chain
    # starts from genesis, not the dead leader's head
    clock.t += 20.0
    assert standby.tick() is True
    assert standby.takeovers == 1
    assert standby.scheduler.ha_role == "active"
    assert standby.scheduler.audit.ledger.cursor() == 0
    assert standby.scheduler.audit.ledger.head_hash() \
        != leader.audit.ledger.head_hash()


# -- the failover soak ---------------------------------------------------------


class MidFlushKiller:
    """Leader-only client facade: when armed, the next bulk bind commits
    its first half and then the 'process' dies — the half-flushed batch
    a real crash leaves behind."""

    def __init__(self, inner):
        self.inner = inner
        self.armed = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def bind_all(self, pairs, fence_token=None):
        if self.armed and len(pairs) > 1:
            self.armed = False
            self.inner.bind_all(pairs[:len(pairs) // 2],
                                fence_token=fence_token)
            raise Killed("died mid-flush")
        return self.inner.bind_all(pairs, fence_token=fence_token)


def _arm_kill(leader, phase):
    """Wire the simulated death into the chosen drain phase."""
    if phase == "host_build":
        orig = leader.builder.build

        def die(*a, **k):
            leader.builder.build = orig
            raise Killed("died in host build")
        leader.builder.build = die
    elif phase == "device":
        # dispatched, never committed: results die in flight
        def die(*a, **k):
            raise Killed("died before commit")
        leader._commit_next = die
    elif phase == "commit":
        # committed locally (cache + dispatcher enqueue), never flushed
        orig_flush = leader.dispatcher.flush

        def die_flush(*a, **k):
            if len(leader.dispatcher):
                raise Killed("died before the API flush")
            return orig_flush(*a, **k)
        leader.dispatcher.flush = die_flush
    elif phase == "mid_flush":
        leader.client.armed = True
    else:                            # pragma: no cover
        raise AssertionError(phase)


@pytest.mark.slow
@pytest.mark.parametrize("phase",
                         ["host_build", "device", "commit", "mid_flush"])
def test_failover_kill_matrix(phase):
    """Kill the leader at every drain phase: the warm spare takes over
    and the final assignment map is IDENTICAL to an unkilled run — zero
    double-binds, zero oracle divergence at 100% sampling, hash chain
    intact across the spliced handoff."""
    # unkilled twin: one scheduler, same store mutations
    api0 = APIServer()
    _nodes(api0, n=8, cpu=32, mem="64Gi")
    clock0 = Clock()
    ref = _audited(_no_sleep(Scheduler(api0, batch_size=32, clock=clock0)))
    _create(api0, _pod_specs(20, seed=100, prefix="a"))
    ref.schedule_pending()
    _create(api0, _pod_specs(24, seed=200, prefix="b"))
    _drive_to_quiescence(api0, ref, clock0, want_bound=44)
    baseline = _assignments(api0)
    assert len(baseline) == 44 and all(baseline.values())

    # killed run: leader + warm standby on one store
    api = APIServer()
    _nodes(api, n=8, cpu=32, mem="64Gi")
    clock = Clock()
    client = MidFlushKiller(api) if phase == "mid_flush" else api
    leader = _audited(_no_sleep(Scheduler(client, batch_size=32,
                                          clock=clock)))
    el_a = LeaderElector(api, "sched-a", clock=clock)
    fence_dispatcher(leader.dispatcher, el_a)
    assert el_a.tick() is True
    _create(api, _pod_specs(20, seed=100, prefix="a"))
    leader.schedule_pending()

    standby = _standby(api, clock, ledger=leader.audit.ledger)
    assert standby.tick() is False
    standby.sync()                   # warm: cache + arrays + JIT minted

    _create(api, _pod_specs(24, seed=200, prefix="b"))
    _arm_kill(leader, phase)
    with pytest.raises(Killed):
        leader.schedule_pending()
    # the leader is dead: it never ticks, renews or flushes again
    clock.t += 20.0                  # its lease expires
    assert standby.tick() is True    # takeover: tail drain, splice,
    sched_b = standby.scheduler      # delta resync, promote
    assert sched_b.ha_role == "active"
    assert standby.takeovers == 1
    assert standby.failover_s is not None

    _drive_to_quiescence(api, sched_b, clock, want_bound=44)

    # assignment-set parity with the unkilled twin
    assert _assignments(api) == baseline
    # zero double-binds: every pod bound exactly once, ever
    assert api.binding_count == 44
    assert not sched_b.cache.assumed_pods
    assert sched_b.reconcile() == []
    # zero shadow-oracle divergence on BOTH sides of the handoff
    for sched in (leader, sched_b):
        for kind in ("assignment", "reason", "verdict"):
            assert sched.metrics.oracle_divergence.value(kind) == 0, kind
    # the spliced hash chain verifies across the handoff, and the
    # successor's chain really does continue the dead leader's
    assert leader.audit.ledger.verify()
    assert sched_b.audit.ledger.verify()
    assert sched_b.metrics.ha_failover.count() >= 1
    assert sched_b.metrics.leader_transitions.value("acquired") == 1
