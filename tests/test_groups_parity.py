"""Device ↔ oracle parity for the group kernels (ops/groups.py):
PodTopologySpread and InterPodAffinity, including clusters PRE-POPULATED
with spread/affinity/anti-affinity pods — the adversarial setting where the
symmetric semantics (existing pods vetoing/scoring incoming ones) bite.

Every device assignment must land in the host oracle's argmax set on the
same evolving cluster state (the oracle is the transliterated Go-semantics
runtime; see test_program_parity.py for the lean-program counterpart).
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.framework.runtime import Framework, schedule_pod
from kubernetes_tpu.framework.types import FitError
from kubernetes_tpu.ops.groups import to_device
from kubernetes_tpu.ops.program import (ScoreConfig, initial_carry,
                                        pod_rows_from_batch, run_batch)
from kubernetes_tpu.plugins import noderesources as nr
from kubernetes_tpu.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.plugins.node_basics import (NodeName, NodePorts,
                                                NodeUnschedulable,
                                                TaintToleration)
from kubernetes_tpu.plugins.nodeaffinity import NodeAffinity
from kubernetes_tpu.plugins.podtopologyspread import PodTopologySpread
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"

WEIGHTS = {"TaintToleration": 3, "NodeAffinity": 2, "PodTopologySpread": 2,
           "InterPodAffinity": 2, "NodeResourcesFit": 1,
           "NodeResourcesBalancedAllocation": 1}


def full_framework():
    return Framework("default-scheduler",
                     [NodeUnschedulable(), NodeName(), TaintToleration(),
                      NodeAffinity(), NodePorts(), nr.Fit(),
                      nr.BalancedAllocation(), PodTopologySpread(),
                      InterPodAffinity()],
                     weights=WEIGHTS)


def assert_group_parity(nodes, existing, batch_pods, cfg=ScoreConfig()):
    """`existing`: [(pod, node_name)] pre-bound pods. Runs the device batch
    with group kernels and checks every decision against the oracle."""
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for pod, node_name in existing:
        pod.spec.node_name = node_name
        cache.add_pod(pod)
    snap = Snapshot()
    cache.update_snapshot(snap)

    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    batch = builder.build(batch_pods)
    assert not batch.host_fallback.any(), "test pods must be tensorizable"

    gd_np, gc_np = builder.groups.build_dev(snap)
    gd, gc = to_device(gd_np), to_device(gc_np)
    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    carry, assignments = run_batch(cfg, na, initial_carry(na, gc), xs, table,
                                   groups=gd)
    assignments = np.asarray(assignments)[:len(batch_pods)]

    fwk = full_framework()
    for i, pod in enumerate(batch_pods):
        chosen = assignments[i]
        node_name = state.node_names[chosen] if chosen >= 0 else None
        try:
            result = schedule_pod(fwk, CycleState(), pod, snap.node_info_list)
        except FitError:
            assert node_name is None, (
                f"pod {pod.name}: device chose {node_name}, oracle found none")
            continue
        assert node_name is not None, (
            f"pod {pod.name}: device found none, oracle chose "
            f"{result.suggested_host} (argmax {sorted(result.argmax_set)})")
        assert node_name in result.argmax_set, (
            f"pod {pod.name}: device chose {node_name} "
            f"(score {result.scores.get(node_name)}), oracle argmax "
            f"{sorted(result.argmax_set)} scores {result.scores}")
        pod.spec.node_name = node_name
        cache.assume_pod(pod)
        cache.update_snapshot(snap)
    return assignments


def zoned_nodes(n, zones=2):
    return [make_node(f"n{i}").capacity({"cpu": "16", "memory": "32Gi",
                                         "pods": 110})
            .zone(f"z{i % zones}").label(HOSTNAME, f"n{i}").obj()
            for i in range(n)]


class TestSpreadFilterParity:
    def test_zone_spread_balances(self):
        nodes = zoned_nodes(4)
        pods = [make_pod(f"p{i}").label("app", "web")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "web"})
                .req({"cpu": "500m"}).obj() for i in range(8)]
        a = assert_group_parity(nodes, [], pods)
        assert (a >= 0).all()

    def test_existing_pods_skew_counts(self):
        nodes = zoned_nodes(4)
        # z0 already holds 3 matching pods → first incoming must go z1
        existing = [(make_pod(f"e{i}").label("app", "web")
                     .req({"cpu": "100m"}).obj(), "n0") for i in range(3)]
        pods = [make_pod(f"p{i}").label("app", "web")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "web"})
                .req({"cpu": "500m"}).obj() for i in range(4)]
        a = assert_group_parity(nodes, existing, pods)
        assert (a >= 0).all()

    def test_dual_constraint_zone_and_hostname(self):
        nodes = zoned_nodes(6, zones=3)
        pods = [make_pod(f"p{i}").label("app", "api")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "api"})
                .spread_constraint(2, HOSTNAME, "DoNotSchedule", {"app": "api"})
                .req({"cpu": "250m"}).obj() for i in range(9)]
        assert_group_parity(nodes, [], pods)

    def test_skew_exhaustion_unschedulable(self):
        # one zone only: maxSkew 1 with min over a single domain never blocks
        # — use two zones where one is full by capacity to force skew failure
        nodes = [make_node("a0").capacity({"cpu": "1", "pods": 110}).zone("z0")
                 .label(HOSTNAME, "a0").obj(),
                 make_node("b0").capacity({"cpu": "16", "pods": 110}).zone("z1")
                 .label(HOSTNAME, "b0").obj()]
        pods = [make_pod(f"p{i}").label("g", "x")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"g": "x"})
                .req({"cpu": "900m"}).obj() for i in range(4)]
        a = assert_group_parity(nodes, [], pods)
        # z0 fits one pod; after z1 gets 2 (skew 1→2 vs z0's 1) the rest park
        assert (a >= 0).sum() == 3

    def test_min_domains(self):
        # minDomains=3 with only 2 zones ⇒ global min treated as 0
        nodes = zoned_nodes(4)
        pods = [make_pod(f"p{i}").label("md", "y")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"md": "y"},
                                   min_domains=3)
                .req({"cpu": "100m"}).obj() for i in range(3)]
        assert_group_parity(nodes, [], pods)


class TestSpreadScoreParity:
    def test_schedule_anyway_prefers_low_count(self):
        nodes = zoned_nodes(4)
        existing = [(make_pod(f"e{i}").label("app", "soft")
                     .req({"cpu": "100m"}).obj(), "n0") for i in range(4)]
        pods = [make_pod(f"p{i}").label("app", "soft")
                .spread_constraint(1, ZONE, "ScheduleAnyway", {"app": "soft"})
                .req({"cpu": "500m"}).obj() for i in range(6)]
        assert_group_parity(nodes, existing, pods)

    def test_mixed_filter_and_score_constraints(self):
        nodes = zoned_nodes(6, zones=3)
        pods = [make_pod(f"p{i}").label("app", "mix")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "mix"})
                .spread_constraint(1, HOSTNAME, "ScheduleAnyway", {"app": "mix"})
                .req({"cpu": "250m"}).obj() for i in range(8)]
        assert_group_parity(nodes, [], pods)


class TestInterPodAffinityParity:
    def test_required_affinity_colocates(self):
        nodes = zoned_nodes(4)
        existing = [(make_pod("anchor").label("app", "db")
                     .req({"cpu": "100m"}).obj(), "n1")]
        pods = [make_pod(f"p{i}").label("app", "web")
                .pod_affinity(ZONE, {"app": "db"})
                .req({"cpu": "500m"}).obj() for i in range(3)]
        a = assert_group_parity(nodes, existing, pods)
        # all must land in the anchor's zone (z1 = n1, n3)
        assert all(int(x) in (1, 3) for x in a)

    def test_self_affinity_escape_hatch(self):
        # no matching pods anywhere; pod matches its own term → schedulable
        nodes = zoned_nodes(2)
        pods = [make_pod(f"p{i}").label("app", "solo")
                .pod_affinity(ZONE, {"app": "solo"})
                .req({"cpu": "100m"}).obj() for i in range(3)]
        a = assert_group_parity(nodes, [], pods)
        assert (a >= 0).all()
        # followers must co-locate with the first pod's zone
        zones = {0: "z0", 1: "z1"}
        assert len({zones[int(x) % 2] for x in a}) == 1

    def test_required_anti_affinity_excludes(self):
        nodes = zoned_nodes(4)
        pods = [make_pod(f"p{i}").label("app", "lonely")
                .pod_affinity(ZONE, {"app": "lonely"}, anti=True)
                .req({"cpu": "100m"}).obj() for i in range(3)]
        a = assert_group_parity(nodes, [], pods)
        # 2 zones → only 2 can bind, one per zone
        assert (a >= 0).sum() == 2

    def test_existing_anti_affinity_vetoes_plain_pod(self):
        nodes = zoned_nodes(2)
        existing = [(make_pod("guard").label("app", "g")
                     .pod_affinity(ZONE, {"app": "web"}, anti=True)
                     .req({"cpu": "100m"}).obj(), "n0")]
        pods = [make_pod("victim").label("app", "web").req({"cpu": "100m"}).obj(),
                make_pod("free").label("app", "other").req({"cpu": "100m"}).obj()]
        a = assert_group_parity(nodes, existing, pods)
        assert int(a[0]) == 1  # pushed out of the guard's zone
        assert int(a[1]) >= 0

    def test_preferred_affinity_scores(self):
        nodes = zoned_nodes(4)
        existing = [(make_pod("anchor").label("app", "cache")
                     .req({"cpu": "100m"}).obj(), "n2")]
        pods = [make_pod(f"p{i}").label("app", "fe")
                .preferred_pod_affinity(ZONE, {"app": "cache"}, weight=50)
                .req({"cpu": "250m"}).obj() for i in range(4)]
        assert_group_parity(nodes, [], pods)
        assert_group_parity(nodes, existing, pods)

    def test_symmetric_preferred_scoring_of_plain_pods(self):
        # existing pod carries preferred affinity toward app=web: an incoming
        # PLAIN app=web pod is pulled toward it (scoring.go:81-124 symmetry)
        nodes = zoned_nodes(4)
        existing = [(make_pod("magnet").label("app", "m")
                     .preferred_pod_affinity(ZONE, {"app": "web"}, weight=80)
                     .req({"cpu": "100m"}).obj(), "n3")]
        pods = [make_pod(f"p{i}").label("app", "web").req({"cpu": "250m"}).obj()
                for i in range(3)]
        assert_group_parity(nodes, existing, pods)

    def test_hard_affinity_weight_symmetry(self):
        # existing pod with REQUIRED affinity toward app=web contributes
        # hardPodAffinityWeight symmetric score to incoming web pods
        nodes = zoned_nodes(4)
        existing = [(make_pod("req").label("app", "req")
                     .pod_affinity(ZONE, {"app": "web"})
                     .req({"cpu": "100m"}).obj(), "n1")]
        pods = [make_pod(f"p{i}").label("app", "web").req({"cpu": "250m"}).obj()
                for i in range(3)]
        assert_group_parity(nodes, existing, pods)


class TestMixedGroupFuzz:
    """The adversarial fuzz VERDICT asked for: randomized clusters
    pre-populated with spread/affinity/anti-affinity pods, randomized mixed
    batches. Every decision checked against the oracle."""

    @pytest.mark.parametrize("seed", range(5))
    def test_fuzz(self, seed):
        rng = random.Random(1000 + seed)
        n_nodes = rng.randint(4, 10)
        zones = rng.randint(2, 3)
        nodes = []
        for i in range(n_nodes):
            w = (make_node(f"n{i}")
                 .capacity({"cpu": str(rng.choice([4, 8, 16])),
                            "memory": f"{rng.choice([8, 16, 32])}Gi",
                            "pods": 110})
                 .zone(f"z{i % zones}").label(HOSTNAME, f"n{i}"))
            if rng.random() < 0.2:
                w = w.label("disk", rng.choice(["ssd", "hdd"]))
            nodes.append(w.obj())

        apps = ["web", "db", "cache"]
        existing = []
        for i in range(rng.randint(0, 6)):
            w = (make_pod(f"e{i}").label("app", rng.choice(apps))
                 .req({"cpu": "100m"}))
            r = rng.random()
            if r < 0.25:
                w = w.pod_affinity(ZONE, {"app": rng.choice(apps)}, anti=True)
            elif r < 0.5:
                w = w.preferred_pod_affinity(
                    ZONE, {"app": rng.choice(apps)},
                    weight=rng.randint(1, 100), anti=rng.random() < 0.5)
            elif r < 0.7:
                w = w.spread_constraint(rng.randint(1, 2), ZONE,
                                        "DoNotSchedule",
                                        {"app": w.pod.metadata.labels["app"]})
            existing.append((w.obj(), f"n{rng.randrange(n_nodes)}"))

        pods = []
        for i in range(rng.randint(4, 16)):
            app = rng.choice(apps)
            w = make_pod(f"p{i}").label("app", app).req(
                {"cpu": rng.choice(["100m", "500m", "1"]),
                 "memory": rng.choice(["128Mi", "1Gi"])})
            r = rng.random()
            if r < 0.2:
                w = w.spread_constraint(
                    rng.randint(1, 2), ZONE,
                    rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                    {"app": app})
            elif r < 0.35:
                w = w.pod_affinity(ZONE, {"app": rng.choice(apps)},
                                   anti=rng.random() < 0.5)
            elif r < 0.5:
                w = w.preferred_pod_affinity(ZONE, {"app": rng.choice(apps)},
                                             weight=rng.randint(1, 100),
                                             anti=rng.random() < 0.5)
            if rng.random() < 0.2:
                w = w.node_selector({"disk": rng.choice(["ssd", "hdd"])})
            pods.append(w.obj())
        assert_group_parity(nodes, existing, pods)


class TestGroupSigCacheInterplay:
    """The signature fast path caches only carry-independent kernels; group
    kernels are carry-coupled and must stay live. fast == slow with groups."""

    def test_fast_equals_slow_with_spread(self):
        import jax.numpy as jnp
        nodes = zoned_nodes(6, zones=3)
        pods = [make_pod(f"p{i}").label("app", "w")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "w"})
                .req({"cpu": "250m"}).obj() for i in range(12)]
        cache = Cache()
        for n in nodes:
            cache.add_node(n)
        snap = Snapshot()
        cache.update_snapshot(snap)
        state = ClusterState()
        state.apply_snapshot(snap, full=True)
        builder = BatchBuilder(state)
        batch = builder.build(pods)
        assert not batch.host_fallback.any()
        gd_np, gc_np = builder.groups.build_dev(snap)
        gd, gc = to_device(gd_np), to_device(gc_np)
        na = state.device_arrays()
        xs, table = pod_rows_from_batch(batch)
        cfg = ScoreConfig()
        sigs = np.asarray(batch.sig)[:len(pods)]
        assert (np.diff(sigs) == 0).any(), "should exercise the fast path"
        _, fast = run_batch(cfg, na, initial_carry(na, gc), xs, table, groups=gd)
        xs_slow = xs._replace(sig=jnp.zeros_like(xs.sig))
        _, slow = run_batch(cfg, na, initial_carry(na, gc), xs_slow, table,
                            groups=gd)
        np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


class TestMidCarryRowSeeding:
    """A NEW signature appearing while the device carry is resident must get
    its group counts seeded from the live snapshot (scatter_new_rows), with
    prior in-carry placements visible through the host cache."""

    def test_new_spread_signature_mid_stream(self):
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.scheduler import Scheduler
        api = APIServer()
        sched = Scheduler(api, batch_size=16)
        for i in range(4):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": "16", "memory": "32Gi", "pods": 110})
                            .zone(f"z{i % 2}").label(HOSTNAME, f"n{i}").obj())
        # wave 1: establishes a resident carry with groups ON (affinity
        # pod) and THREE signature rows, so wave 2's fourth row stays
        # inside the pow2-4 device bucket and takes the in-place scatter
        # path (not a full reseed)
        api.create_pod(make_pod("a0").label("app", "web")
                       .pod_affinity(ZONE, {"app": "web"}, anti=True)
                       .req({"cpu": "100m"}).obj())
        for i in range(4):
            api.create_pod(make_pod(f"w1-{i}").label("app", "plain")
                           .req({"cpu": "100m"}).obj())
        for i in range(2):
            api.create_pod(make_pod(f"w1b-{i}").label("app", "other")
                           .req({"cpu": "200m"}).obj())
        assert sched.schedule_pending() == 7
        assert sched._device_carry is not None
        assert sched.builder.groups.device_rows() == 4
        seeded_before = sched._seeded_rows
        import kubernetes_tpu.ops.groups as groups_mod
        scatter_calls = []
        orig_scatter = groups_mod.scatter_new_rows
        groups_mod.scatter_new_rows = (
            lambda *a, **k: scatter_calls.append(1) or orig_scatter(*a, **k))
        # wave 2: a NEW spread signature arrives; the carry must stay
        # resident and the new row gets seeded in place
        for i in range(6):
            api.create_pod(make_pod(f"w2-{i}").label("app", "spread")
                           .spread_constraint(1, ZONE, "DoNotSchedule",
                                              {"app": "spread"})
                           .req({"cpu": "250m"}).obj())
        try:
            assert sched.schedule_pending() == 6
        finally:
            groups_mod.scatter_new_rows = orig_scatter
        assert scatter_calls, "new row must seed via scatter, not reseed"
        assert sched._seeded_rows > seeded_before
        # skew must hold across zones
        zone_of = {f"n{i}": f"z{i % 2}" for i in range(4)}
        counts = {}
        for name, p in api.pods.items():
            if name.startswith("default/w2-"):
                z = zone_of[p.spec.node_name]
                counts[z] = counts.get(z, 0) + 1
        assert abs(counts.get("z0", 0) - counts.get("z1", 0)) <= 1
        assert sched.host_scheduled == 0
        assert sched.reconcile() == []
