"""NodeResourcesFit filter + scoring oracle tests.

Expected values mirror the reference's unit tests for
noderesources/fit_test.go and least_allocated/balanced_allocation tests
(recomputed by hand from the documented formulas, not copied)."""

from kubernetes_tpu.api import resources as res
from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.types import NodeInfo, PodInfo
from kubernetes_tpu.plugins import noderesources as nr
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def node_info(cpu="32", memory="64Gi", pods=110, **extra) -> NodeInfo:
    caps = {"cpu": cpu, "memory": memory, "pods": pods}
    caps.update(extra)
    return NodeInfo(node=make_node().capacity(caps).obj())


def add_pod(ni: NodeInfo, cpu="0", memory="0"):
    ni.add_pod(PodInfo.of(make_pod().req({"cpu": cpu, "memory": memory}).obj()))


class TestFitFilter:
    def run(self, pod, ni):
        f = nr.Fit()
        cs = CycleState()
        f.pre_filter(cs, pod, [ni])
        return f.filter(cs, pod, ni)

    def test_fits(self):
        ni = node_info()
        pod = make_pod().req({"cpu": "1", "memory": "1Gi"}).obj()
        assert self.run(pod, ni).is_success()

    def test_insufficient_cpu(self):
        ni = node_info(cpu="2")
        add_pod(ni, cpu="1500m")
        pod = make_pod().req({"cpu": "1"}).obj()
        st = self.run(pod, ni)
        assert st.code == Code.UNSCHEDULABLE
        assert "Insufficient cpu" in st.reasons

    def test_unresolvable_when_bigger_than_node(self):
        ni = node_info(cpu="2")
        pod = make_pod().req({"cpu": "4"}).obj()
        st = self.run(pod, ni)
        assert st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_too_many_pods(self):
        ni = node_info(pods=1)
        add_pod(ni)
        pod = make_pod().req({"cpu": "1"}).obj()
        st = self.run(pod, ni)
        assert st.code == Code.UNSCHEDULABLE
        assert "Too many pods" in st.reasons

    def test_zero_request_only_checks_pods(self):
        ni = node_info(cpu="1")
        add_pod(ni, cpu="1")  # node full on cpu
        pod = make_pod().obj()  # best-effort
        assert self.run(pod, ni).is_success()

    def test_extended_resource(self):
        ni = node_info(**{"example.com/gpu": 2})
        add_pod(ni)
        pod = make_pod().req({"cpu": "1", "example.com/gpu": 4}).obj()
        st = self.run(pod, ni)
        assert st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert "Insufficient example.com/gpu" in st.reasons

    def test_ignored_extended_resource(self):
        ni = node_info()
        pod = make_pod().req({"cpu": "1", "example.com/gpu": 4}).obj()
        f = nr.Fit(nr.FitArgs(ignored_resources=frozenset({"example.com/gpu"})))
        cs = CycleState()
        f.pre_filter(cs, pod, [ni])
        assert f.filter(cs, pod, ni).is_success()


class TestLeastAllocated:
    def score(self, pod, ni, args=None):
        f = nr.Fit(args)
        cs = CycleState()
        f.pre_score(cs, pod, [ni])
        s, st = f.score(cs, pod, ni)
        assert st.is_success()
        return s

    def test_empty_node_max_score(self):
        # cpu: (4000-1000)*100/4000 = 75 ; mem: (10000-2000)*100/10000 = 80
        ni = node_info(cpu="4", memory=10000)
        pod = make_pod().req({"cpu": "1", "memory": 2000}).obj()
        assert self.score(pod, ni) == (75 + 80) // 2

    def test_with_existing_usage(self):
        # requested(after pod) cpu = 3000/4000 → (4000-3000)*100/4000 = 25
        # mem = 5000/10000 → 50 → avg 37 (int division of sum by weight)
        ni = node_info(cpu="4", memory=10000)
        add_pod(ni, cpu="2", memory=3000)
        pod = make_pod().req({"cpu": "1", "memory": 2000}).obj()
        assert self.score(pod, ni) == (25 + 50) // 2

    def test_overcommitted_scores_zero(self):
        ni = node_info(cpu="1", memory=1000)
        pod = make_pod().req({"cpu": "2", "memory": 2000}).obj()
        assert self.score(pod, ni) == 0

    def test_nonzero_defaults_for_best_effort(self):
        # best-effort pod gets 100m/200Mi defaults in scoring
        ni = node_info(cpu="1", memory=str(400 * 2**20))
        pod = make_pod().obj()
        # cpu: (1000-100)*100/1000 = 90 ; mem: (400Mi-200Mi)*100/400Mi = 50
        assert self.score(pod, ni) == (90 + 50) // 2


class TestBalancedAllocation:
    def score(self, pod, ni):
        p = nr.BalancedAllocation()
        cs = CycleState()
        st = p.pre_score(cs, pod, [ni])
        if st.is_skip():
            return None
        s, st = p.score(cs, pod, ni)
        assert st.is_success()
        return s

    def test_perfectly_balanced(self):
        ni = node_info(cpu="4", memory=4000)
        pod = make_pod().req({"cpu": "2", "memory": 2000}).obj()
        # fractions 0.5/0.5 → std 0 → 100
        assert self.score(pod, ni) == 100

    def test_imbalanced(self):
        ni = node_info(cpu="4", memory=4000)
        pod = make_pod().req({"cpu": "3", "memory": 1000}).obj()
        # fractions 0.75/0.25 → std = |0.75-0.25|/2 = 0.25 → int(0.75*100) = 75
        assert self.score(pod, ni) == 75

    def test_best_effort_skipped(self):
        ni = node_info()
        pod = make_pod().obj()
        assert self.score(pod, ni) is None


class TestMostAllocated:
    def test_most_allocated(self):
        ni = node_info(cpu="4", memory=10000)
        pod = make_pod().req({"cpu": "1", "memory": 2000}).obj()
        f = nr.Fit(nr.FitArgs(scoring_strategy=nr.MOST_ALLOCATED))
        cs = CycleState()
        f.pre_score(cs, pod, [ni])
        s, _ = f.score(cs, pod, ni)
        # cpu 1000/4000 → 25 ; mem 2000/10000 → 20 → 22
        assert s == (25 + 20) // 2
