"""Drain compiler (kubernetes_tpu/compiler/) — ISSUE 8 standing gates.

The compiler maps ANY pod mix to a static device program; this suite
holds its exactness and its plumbing:

* seeded fuzz over >4-signature mixed drains — 8/12/16 INTERACTING
  signatures, group + group-free + host-port rows — with bit parity
  between the plan program (run_plan) and the oracle-verified scan
  (run_batch), plus a direct triangle against the host oracle framework;
* scheduler-level: a 16-signature group-free mixed drain executes as
  compiled device dispatches with ZERO host-greedy fallbacks; gang +
  group + plain traffic in one queue drain stays bit-identical to the
  reference (gates-off) path;
* the pad-bucket lattice at a pow2 edge (exactly 8 signatures vs 9);
* SurfaceCache generation-diff retention: steady-state drains no longer
  clear the per-signature surfaces (the scheduler.py:1661 fix);
* plan-cache metrics + a transfer-guard gate run (rails on, ambient
  jax.transfer_guard("disallow"), zero fallbacks).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.analysis.rails import GLOBAL as RAILS
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.compiler import PLAN_MAX_SIGS, DrainCompiler
from kubernetes_tpu.ops.groups import to_device
from kubernetes_tpu.ops.hostgreedy import static_norm_ok
from kubernetes_tpu.ops.program import (ScoreConfig, WaveXs, initial_carry,
                                        pod_rows_from_batch, run_batch,
                                        run_plan)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState, pow2_at_least
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


def _setup(nodes, existing):
    cache = Cache()
    for nd in nodes:
        cache.add_node(nd)
    for pod, node_name in existing:
        pod.spec.node_name = node_name
        cache.add_pod(pod)
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    return state, snap


def _nodes(n, zones, cpu=16, pods=40):
    return [(make_node(f"n{i}")
             .capacity({"cpu": cpu, "memory": "32Gi", "pods": pods})
             .zone(f"z{i % zones}")
             .label(HOSTNAME, f"n{i}").obj()) for i in range(n)]


def plan_vs_scan(nodes, existing, pods, cfg=ScoreConfig()):
    """Assert the plan program reproduces run_batch's assignments exactly
    for the FULL mixed drain (any signature count, host-port rows
    included); returns the assignments."""
    state, snap = _setup(nodes, existing)
    builder = BatchBuilder(state)
    batch = builder.build(pods)
    assert not batch.host_fallback.any(), "fuzz pods must be tensorizable"
    gd_np, gc_np = builder.groups.build_dev(snap)
    gd, gc = to_device(gd_np), to_device(gc_np)
    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    fam = builder.groups.families(snap)
    n = len(pods)

    _, scan_out = run_batch(cfg, na, initial_carry(na, gc), xs, table,
                            groups=gd, fam=fam)
    scan_out = np.asarray(scan_out)[:n]

    uniq = list(dict.fromkeys(int(t) for t in batch.tidx[:n]))
    has_ports = bool((batch.sig[:n] == 0).any())
    norm_live = not all(
        static_norm_ok(state.ensure_arrays(), builder.table.pref_weight[u])
        for u in uniq)
    B = pow2_at_least(n)
    S = pow2_at_least(len(uniq), 2)
    assert S <= PLAN_MAX_SIGS
    wt_list = (uniq + [uniq[-1]] * S)[:S]
    slot = {}
    for s, u in enumerate(wt_list):
        slot.setdefault(u, s)
    widx = np.zeros((B,), np.int32)
    for k in range(n):
        widx[k] = slot[int(batch.tidx[k])]
    widx[n:] = widx[n - 1]
    valid = np.zeros((B,), bool)
    valid[:n] = True
    compiler = DrainCompiler(state=state, builder=builder, gates=_GATES)
    statics = compiler.surfaces.stacked(na, table, tuple(wt_list))
    wxs = WaveXs(valid=jnp.asarray(valid), widx=jnp.asarray(widx))
    _, packed = run_plan(
        cfg, na, initial_carry(na, gc), wxs, table,
        jnp.asarray(np.array(wt_list, np.int32)), gd, statics, fam,
        norm_live, has_groups=True, has_ports=has_ports)
    plan_out = np.asarray(packed)[:n]
    assert (plan_out == scan_out).all(), (
        "run_plan diverged", len(uniq), scan_out.tolist(),
        plan_out.tolist())
    return scan_out


class _Gates:
    def enabled(self, name):
        return name != "SanitizerRails"


_GATES = _Gates()


def _mixed_pods(rng: random.Random, idx: int, n_sigs: int, n_pods: int,
                with_ports=False):
    """`n_sigs` INTERACTING signatures in one drain: a shared spread
    group over rotating cpu requests, an anti-affinity family, plain
    rows, optionally a host-port signature."""
    pods = []
    kinds = []
    for s in range(n_sigs):
        cpu = f"{200 + 75 * s}m"
        r = s % 3
        if r == 0:
            kinds.append(lambda i, s=s, cpu=cpu: (
                make_pod(f"sp{idx}_{s}_{i}")
                .req({"cpu": cpu, "memory": "512Mi"})
                .label("app", "mix")
                .spread_constraint(rng.choice([2, 5]), ZONE,
                                   "DoNotSchedule", {"app": "mix"})
                .obj()))
        elif r == 1:
            kinds.append(lambda i, s=s, cpu=cpu: (
                make_pod(f"an{idx}_{s}_{i}")
                .req({"cpu": cpu, "memory": "256Mi"})
                .label("anti", "y")
                .pod_affinity(ZONE, {"anti": "y"}, anti=True)
                .obj()))
        else:
            kinds.append(lambda i, s=s, cpu=cpu: (
                make_pod(f"pl{idx}_{s}_{i}")
                .req({"cpu": cpu, "memory": "128Mi"})
                .obj()))
    if with_ports:
        kinds[-1] = lambda i: (
            make_pod(f"pt{idx}_{i}")
            .req({"cpu": "150m", "memory": "128Mi"})
            .host_port(9000 + idx)
            .obj())
    for i in range(n_pods):
        pods.append(kinds[i % len(kinds)](i))
    return pods


@pytest.mark.parametrize("block", range(4))
def test_high_signature_fuzz(block):
    """≥40 seeded scenarios of 8/12/16 interacting signatures (groups +
    group-free + host-port rows interleaved): run_plan ≡ the
    oracle-verified scan, bit for bit."""
    rng = random.Random(7000 + block)
    for k in range(10):
        idx = block * 10 + k
        n_sigs = rng.choice([8, 12, 16])
        n_pods = rng.randint(max(n_sigs, 16), 40)
        nodes = _nodes(rng.choice([9, 12, 16]), rng.choice([3, 4]),
                       cpu=rng.choice([16, 24]))
        with_ports = rng.random() < 0.3
        pods = _mixed_pods(rng, idx, n_sigs, n_pods, with_ports=with_ports)
        plan_vs_scan(nodes, [], pods)


def test_pad_bucket_boundary():
    """Signature count exactly AT a pow2 edge (8 → lattice 8) and one
    past it (9 → lattice 16): both exact, and the padded lattice width
    is what the compiler promises."""
    rng = random.Random(42)
    nodes = _nodes(12, 4, cpu=32)
    for n_sigs, expect_s in ((8, 8), (9, 16)):
        pods = _mixed_pods(rng, 100 + n_sigs, n_sigs, 36)
        plan_vs_scan(nodes, [], pods)
        assert pow2_at_least(n_sigs, 2) == expect_s


def test_plan_vs_host_oracle_direct():
    """Close the triangle: an 8-signature mixed drain against the actual
    host oracle framework (verdicts AND placements), not just the scan."""
    from kubernetes_tpu.framework.interface import CycleState
    from kubernetes_tpu.framework.runtime import schedule_pod
    from kubernetes_tpu.framework.types import FitError
    from tests.test_groups_parity import full_framework

    rng = random.Random(11)
    nodes = _nodes(9, 3)
    pods = _mixed_pods(rng, 0, 8, 24)
    out = plan_vs_scan(nodes, [], pods)

    cache = Cache()
    for nd in nodes:
        cache.add_node(nd)
    fwk = full_framework()
    snap = Snapshot()
    for i, pod in enumerate(pods):
        cache.update_snapshot(snap)
        try:
            result = schedule_pod(fwk, CycleState(), pod,
                                  snap.node_info_list)
            chosen = result.suggested_host
        except FitError:
            chosen = None
        if out[i] < 0:
            assert chosen is None, (i, chosen)
        else:
            assert chosen == f"n{out[i]}", (i, chosen, out[i])
            cache.add_pod(pod.with_node_name(chosen))


def _mk_sched(nodes=16, zones=4, cpu=32, **kw):
    api = APIServer()
    sched = Scheduler(api, batch_size=64, **kw)
    sched.wave_min_span = 4
    for nd in _nodes(nodes, zones, cpu=cpu, pods=80):
        api.create_node(nd)
    sched.prime()
    return api, sched


class TestSchedulerPlans:
    def test_16_sig_group_free_zero_host_greedy(self):
        """Acceptance: a group-free mixed drain with 16 distinct
        signatures executes as compiled device dispatches — zero
        _try_host_greedy fallbacks, zero host-path pods, every span a
        plan program."""
        api, sched = _mk_sched()
        for i in range(48):
            k = i % 16
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": f"{100 + 25 * k}m",
                                 "memory": "128Mi"}).obj())
        assert sched.schedule_pending() == 48
        assert sched.host_greedy_runs == 0
        assert sched.host_scheduled == 0
        assert sched.device_fallbacks == 0
        kinds = [tuple(e["kinds"]) for e in sched.flight.dump()]
        assert any("wavescan" in k for k in kinds), kinds
        assert not any("scan" in k for k in kinds), kinds
        assert sched.reconcile() == []

    def test_16_sig_interacting_group_drain_compiles(self):
        """The >4-signature cliff itself: 16 INTERACTING signatures
        (shared spread group) run as ONE plan dispatch, not the per-pod
        scan, with exact cache bookkeeping."""
        api, sched = _mk_sched()
        for i in range(48):
            k = i % 16
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": f"{100 + 25 * k}m",
                                 "memory": "128Mi"})
                           .label("app", "mix")
                           .spread_constraint(5, ZONE, "DoNotSchedule",
                                              {"app": "mix"}).obj())
        assert sched.schedule_pending() == 48
        assert sched.host_greedy_runs == 0
        kinds = [tuple(e["kinds"]) for e in sched.flight.dump()]
        assert any(k == ("wavescan",) for k in kinds), kinds
        assert sched.reconcile() == []
        from kubernetes_tpu.perf.ledger import GLOBAL as LEDGER
        assert "run_plan" in LEDGER.kernels

    def test_gate_parity_high_signature_mixed(self):
        """Plan execution ≡ the reference path: the same 12-signature
        group+plain traffic with the wave/batching gates off binds every
        pod to the identical node."""
        def run(wave_on):
            api, sched = _mk_sched()
            sched.feature_gates.set("SpeculativeWavePlacement", wave_on)
            rng = random.Random(5)
            for i, p in enumerate(_mixed_pods(rng, 1, 12, 60)):
                api.create_pod(p)
                if i % 30 == 29:
                    sched.schedule_pending(wait=False)
            sched.schedule_pending()
            return {p.metadata.name: p.spec.node_name
                    for p in api.pods.values()}

        assert run(True) == run(False)

    def test_gang_group_plain_one_drain_parity(self):
        """Gang + group + plain rows arriving together: the gang extracts
        into its all-or-nothing dispatch, the rest compiles into plan
        spans — end state identical to the reference Permit-barrier path
        (all device tiers off)."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload

        def run(device_on):
            api = APIServer()
            sched = Scheduler(api, batch_size=128)
            sched.wave_min_span = 4
            if not device_on:
                sched.feature_gates.set("SpeculativeWavePlacement", False)
                sched.feature_gates.set("GangDevicePlacement", False)
                sched.gang_device_enabled = False
            for nd in _nodes(16, 4, cpu=32, pods=80):
                api.create_node(nd)
            sched.prime()
            api.create_workload(Workload(
                metadata=ObjectMeta(name="gangA"),
                pod_groups=[PodGroup(name="workers", min_count=8)]))
            pods = []
            for i in range(8):
                pods.append(make_pod(f"g{i}")
                            .req({"cpu": "500m", "memory": "128Mi"})
                            .workload("gangA").obj())
            rng = random.Random(9)
            pods += _mixed_pods(rng, 3, 8, 24)
            for p in pods:
                api.create_pod(p)
            sched.schedule_pending()
            return {p.metadata.name: p.spec.node_name
                    for p in api.pods.values()}

        on = run(True)
        off = run(False)
        assert on == off
        # the gang itself must bind whole (quorum 8/8) on both paths;
        # anti-affinity rows may legitimately exhaust their 4 domains
        assert all(on[f"g{i}"] for i in range(8)), on

    def test_plan_cache_hits_and_pad_waste(self):
        """Identical drain structure → plan cache hit; the pad-waste
        histogram observes every compile."""
        api, sched = _mk_sched()
        m = sched.metrics

        def feed(prefix):
            for i in range(24):
                k = i % 8
                api.create_pod(make_pod(f"{prefix}{i}")
                               .req({"cpu": f"{100 + 25 * k}m",
                                     "memory": "128Mi"}).obj())
        feed("a")
        assert sched.schedule_pending() == 24
        misses0 = m.compiler_plan_cache_misses.value()
        hits0 = m.compiler_plan_cache_hits.value()
        assert misses0 > 0
        feed("b")
        assert sched.schedule_pending() == 24
        assert m.compiler_plan_cache_hits.value() > hits0
        assert m.compiler_plan_cache_misses.value() == misses0
        assert m.compiler_pad_waste.count() > 0

    def test_surface_cache_retained_across_commits(self):
        """The scheduler.py:1661 fix: committed drains bump the staging
        generation but NOT the statics generation — the per-signature
        surfaces survive, so steady-state dispatches recompute none."""
        api, sched = _mk_sched()

        def feed(prefix):
            for i in range(24):
                k = i % 8
                api.create_pod(make_pod(f"{prefix}{i}")
                               .req({"cpu": f"{100 + 25 * k}m",
                                     "memory": "128Mi"})
                               .label("app", "mix")
                               .spread_constraint(5, ZONE, "DoNotSchedule",
                                                  {"app": "mix"}).obj())
        feed("a")
        assert sched.schedule_pending() == 24
        sc = sched.compiler.surfaces
        misses0 = sc.misses
        # force a placement-only staging-generation bump (the carry
        # adoption path) — exactly what cleared the old cache every drain
        gen0 = sched.state.staging_gen
        assert sched.reconcile() == []
        assert sched.state.staging_gen > gen0
        feed("b")
        assert sched.schedule_pending() == 24
        assert sc.misses == misses0                  # surfaces survived
        assert sc.hits > 0
        # a STATIC node change (cordon) must invalidate: correctness
        # before retention
        cordoned = (make_node("n0")
                    .capacity({"cpu": 32, "memory": "32Gi", "pods": 80})
                    .zone("z0").label(HOSTNAME, "n0")
                    .unschedulable().obj())
        api.update_node(cordoned)
        feed("c")
        sched.schedule_pending()
        assert sc.misses > misses0


class TestTransferGuardPlan:
    @pytest.fixture()
    def rails_off_after(self):
        yield
        RAILS.enable(False)

    def test_high_sig_drain_under_ambient_disallow(self, rails_off_after):
        """Transfer-guard gate: a steady >4-signature mixed drain —
        surfaces hoisted lazily inside the dispatch region — completes
        under ambient jax.transfer_guard("disallow") with zero
        fallbacks."""
        from kubernetes_tpu.config import KubeSchedulerConfiguration
        cfg = KubeSchedulerConfiguration(
            feature_gates={"SanitizerRails": True})
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        sched.wave_min_span = 4
        for nd in _nodes(8, 2, cpu=32, pods=110):
            api.create_node(nd)

        def feed(prefix):
            for i in range(32):
                k = i % 8
                api.create_pod(make_pod(f"{prefix}{i}")
                               .req({"cpu": f"{100 + 25 * k}m",
                                     "memory": "64Mi"})
                               .label("app", "mix")
                               .spread_constraint(5, ZONE,
                                                  "ScheduleAnyway",
                                                  {"app": "mix"}).obj())
        feed("warm")
        assert sched.schedule_pending() == 32
        feed("steady")
        with jax.transfer_guard("disallow"):
            assert sched.schedule_pending() == 32
        assert sched.device_fallbacks == 0
        assert sched.host_scheduled == 0
