"""Device-program ↔ host-oracle decision parity.

The hard requirement (BASELINE.json): bind decisions identical to the default
Go plugins. The host runtime (framework/runtime.py) is the transliterated
oracle; here the batched device program's every assignment is checked to land
in the oracle's argmax set on the same evolving cluster state, across
randomized clusters exercising every v1 kernel.
"""

import random

import jax.numpy as jnp

import numpy as np
import pytest

from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.framework.interface import CycleState
from kubernetes_tpu.framework.runtime import Framework, schedule_pod
from kubernetes_tpu.framework.types import FitError, PodInfo
from kubernetes_tpu.ops.program import (ScoreConfig, initial_carry,
                                        pod_rows_from_batch, run_batch)
from kubernetes_tpu.plugins import noderesources as nr
from kubernetes_tpu.plugins.node_basics import (NodeName, NodePorts,
                                                NodeUnschedulable,
                                                TaintToleration)
from kubernetes_tpu.plugins.nodeaffinity import NodeAffinity
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState
from kubernetes_tpu.testing.wrappers import make_node, make_pod

WEIGHTS = {"TaintToleration": 3, "NodeAffinity": 2,
           "NodeResourcesFit": 1, "NodeResourcesBalancedAllocation": 1}


def default_framework():
    return Framework("default-scheduler",
                     [NodeUnschedulable(), NodeName(), TaintToleration(),
                      NodeAffinity(), NodePorts(), nr.Fit(),
                      nr.BalancedAllocation()],
                     weights=WEIGHTS)


def assert_device_matches_oracle(nodes, pods, cfg=ScoreConfig()):
    """Run the device batch; verify each assignment is in the oracle argmax
    set on the same evolving state; apply device choices to the host state."""
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    snap = Snapshot()
    cache.update_snapshot(snap)

    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    batch = builder.build(pods)
    assert not batch.host_fallback.any(), "test pods must be tensorizable"

    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    carry, assignments = run_batch(cfg, na, initial_carry(na), xs, table)
    assignments = np.asarray(assignments)[:len(pods)]  # drop padding rows

    fwk = default_framework()
    for i, pod in enumerate(pods):
        chosen = assignments[i]
        node_name = state.node_names[chosen] if chosen >= 0 else None
        try:
            result = schedule_pod(fwk, CycleState(), pod, snap.node_info_list)
        except FitError:
            assert node_name is None, (
                f"pod {pod.name}: device chose {node_name}, oracle found none")
            continue
        assert node_name is not None, (
            f"pod {pod.name}: device found none, oracle chose "
            f"{result.suggested_host} (argmax {sorted(result.argmax_set)})")
        assert node_name in result.argmax_set, (
            f"pod {pod.name}: device chose {node_name} "
            f"(score {result.scores.get(node_name)}), oracle argmax set "
            f"{sorted(result.argmax_set)} scores {result.scores}")
        # evolve host state with the DEVICE's choice (both are legal picks)
        pod.spec.node_name = node_name
        cache.assume_pod(pod)
        cache.update_snapshot(snap)
    return assignments


class TestBasicParity:
    def test_least_allocated_round_robin(self):
        nodes = [make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
                 for i in range(4)]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj()
                for i in range(12)]
        a = assert_device_matches_oracle(nodes, pods)
        assert (a >= 0).all()
        # perfect balance: 3 pods per node
        assert sorted(np.bincount(a, minlength=4)) == [3, 3, 3, 3]

    def test_capacity_exhaustion(self):
        nodes = [make_node("n0").capacity({"cpu": "2", "memory": "4Gi", "pods": 110}).obj()]
        pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
        a = assert_device_matches_oracle(nodes, pods)
        assert list(a >= 0) == [True, True, False, False]

    def test_pod_count_limit(self):
        nodes = [make_node("n0").capacity({"cpu": "32", "pods": 2}).obj()]
        pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(3)]
        a = assert_device_matches_oracle(nodes, pods)
        assert list(a >= 0) == [True, True, False]

    def test_heterogeneous_capacities(self):
        nodes = [make_node("big").capacity({"cpu": "16", "memory": "32Gi"}).obj(),
                 make_node("small").capacity({"cpu": "2", "memory": "4Gi"}).obj()]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj()
                for i in range(8)]
        assert_device_matches_oracle(nodes, pods)

    def test_best_effort_pods(self):
        # zero requests: balanced-allocation skips, nonzero defaults drive fit
        nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)]
        pods = [make_pod(f"p{i}").req({}).obj() for i in range(6)]
        assert_device_matches_oracle(nodes, pods)


class TestConstraintParity:
    def test_node_name_pinning(self):
        nodes = [make_node(f"n{i}").obj() for i in range(3)]
        p = make_pod("pin2").obj()
        p.spec.node_name = "n2"
        a = assert_device_matches_oracle(nodes, [p])
        assert a[0] == 2

    def test_unschedulable_node(self):
        nodes = [make_node("up").obj(), make_node("down").unschedulable().obj()]
        pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
        a = assert_device_matches_oracle(nodes, pods)
        assert (a == 0).all()

    def test_taints_and_tolerations(self):
        nodes = [make_node("tainted").taint("dedicated", "gpu").obj(),
                 make_node("open").obj()]
        plain = make_pod("plain").req({"cpu": "1"}).obj()
        tolerant = (make_pod("tolerant").req({"cpu": "1"})
                    .toleration(key="dedicated", operator="Equal", value="gpu",
                                effect="NoSchedule").obj())
        a = assert_device_matches_oracle(nodes, [plain, tolerant])
        assert a[0] == 1  # plain pod forced onto open node

    def test_prefer_no_schedule_scoring(self):
        nodes = [make_node("soft").taint("x", "y", effect="PreferNoSchedule").obj(),
                 make_node("clean").obj()]
        pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(2)]
        a = assert_device_matches_oracle(nodes, pods)
        assert a[0] == 1  # clean preferred

    def test_node_selector(self):
        nodes = [make_node("ssd").label("disk", "ssd").obj(),
                 make_node("hdd").label("disk", "hdd").obj()]
        pod = make_pod("p").node_selector({"disk": "ssd"}).req({"cpu": "1"}).obj()
        a = assert_device_matches_oracle(nodes, [pod])
        assert a[0] == 0

    def test_required_node_affinity_in(self):
        nodes = [make_node(f"n{i}").label("zone", f"z{i}").obj() for i in range(3)]
        pod = (make_pod("p").node_affinity_in("zone", ["z1", "z2"])
               .req({"cpu": "1"}).obj())
        a = assert_device_matches_oracle(nodes, [pod])
        assert a[0] in (1, 2)

    def test_preferred_node_affinity(self):
        nodes = [make_node("plain").obj(),
                 make_node("preferred").label("tier", "gold").obj()]
        pod = (make_pod("p").preferred_node_affinity_in("tier", ["gold"], weight=10)
               .req({"cpu": "1"}).obj())
        a = assert_device_matches_oracle(nodes, [pod])
        assert a[0] == 1

    def test_host_ports(self):
        nodes = [make_node(f"n{i}").obj() for i in range(2)]
        pods = [make_pod(f"p{i}").host_port(8080).req({"cpu": "1"}).obj()
                for i in range(3)]
        a = assert_device_matches_oracle(nodes, pods)
        assert sorted(a[:2]) == [0, 1]
        assert a[2] == -1  # both nodes' 8080 taken

    def test_gt_lt_selector(self):
        nodes = [make_node("few").label("gpus", "2").obj(),
                 make_node("many").label("gpus", "8").obj()]
        import kubernetes_tpu.api.types as T
        pod = make_pod("p").req({"cpu": "1"}).obj()
        term = T.NodeSelectorTerm(match_expressions=(
            T.LabelSelectorRequirement("gpus", "Gt", ("4",)),))
        pod.spec.affinity = T.Affinity(node_affinity=T.NodeAffinity(
            required=T.NodeSelector(terms=(term,))))
        a = assert_device_matches_oracle(nodes, [pod])
        assert a[0] == 1


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(5))
    def test_fuzz(self, seed):
        rng = random.Random(seed)
        nodes = []
        for i in range(rng.randint(3, 12)):
            w = make_node(f"n{i}").capacity({
                "cpu": str(rng.choice([2, 4, 8, 16])),
                "memory": f"{rng.choice([4, 8, 16, 32])}Gi",
                "pods": rng.choice([5, 110])})
            if rng.random() < 0.3:
                w = w.label("disk", rng.choice(["ssd", "hdd"]))
            if rng.random() < 0.3:
                w = w.zone(f"z{rng.randint(0, 2)}")
            if rng.random() < 0.2:
                w = w.taint("dedicated", "batch",
                            effect=rng.choice(["NoSchedule", "PreferNoSchedule"]))
            if rng.random() < 0.1:
                w = w.unschedulable()
            nodes.append(w.obj())
        pods = []
        for i in range(rng.randint(5, 30)):
            w = make_pod(f"p{i}").req({
                "cpu": rng.choice(["100m", "500m", "1", "2"]),
                "memory": rng.choice(["128Mi", "1Gi", "2Gi"])})
            if rng.random() < 0.3:
                w = w.node_selector({"disk": rng.choice(["ssd", "hdd"])})
            if rng.random() < 0.3:
                w = w.toleration(key="dedicated", operator="Exists")
            if rng.random() < 0.2:
                w = w.preferred_node_affinity_in(
                    "topology.kubernetes.io/zone", [f"z{rng.randint(0, 2)}"],
                    weight=rng.randint(1, 10))
            if rng.random() < 0.15:
                w = w.host_port(rng.choice([80, 443, 8080]))
            pods.append(w.obj())
        assert_device_matches_oracle(nodes, pods)


class TestSignatureFastPath:
    """The cached fast step must be decision-identical to the full kernels:
    run the same batch with signatures enabled and with signatures zeroed
    (cache disabled) and compare assignments and final carry."""

    def test_identical_pods_fast_equals_slow(self):
        import dataclasses
        nodes = [make_node(f"n{i}").capacity(
            {"cpu": 4 + i % 3, "memory": f"{8 + i % 5}Gi", "pods": 110})
            .zone(f"z{i % 2}").obj() for i in range(12)]
        pods = [make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj()
                for i in range(24)]
        _assert_fast_equals_slow(nodes, pods)

    def test_mixed_signature_runs(self):
        nodes = [make_node(f"n{i}").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 110})
            .taint("soft", "x", "PreferNoSchedule" if i % 3 == 0 else "NoSchedule")
            .obj() for i in range(8)]
        for n in nodes[:4]:
            n.spec.taints.clear()
        pods = []
        for i in range(16):
            w = make_pod(f"p{i}").req({"cpu": "250m"})
            if i % 4 < 2:  # two alternating signature groups in runs of 2
                w = w.toleration(key="soft", operator="Equal", value="x")
            pods.append(w.obj())
        _assert_fast_equals_slow(nodes, pods)


def _assert_fast_equals_slow(nodes, pods):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    snap = Snapshot()
    cache.update_snapshot(snap)
    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    batch = BatchBuilder(state).build(pods)
    assert not batch.host_fallback.any()
    xs, table = pod_rows_from_batch(batch)
    na = state.device_arrays()
    cfg = ScoreConfig()
    # sanity: the batch really contains repeated signatures
    sigs = np.asarray(batch.sig)[:len(pods)]
    assert (np.diff(sigs) == 0).any(), "test should exercise the fast path"
    carry_f, assign_f = run_batch(cfg, na, initial_carry(na), xs, table)
    xs_slow = xs._replace(sig=jnp.zeros_like(xs.sig))
    carry_s, assign_s = run_batch(cfg, na, initial_carry(na), xs_slow, table)
    np.testing.assert_array_equal(np.asarray(assign_f), np.asarray(assign_s))
    for name in ("used", "nonzero_used", "npods", "ports"):
        np.testing.assert_array_equal(
            np.asarray(getattr(carry_f, name)),
            np.asarray(getattr(carry_s, name)), err_msg=name)
