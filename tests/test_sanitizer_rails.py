"""Runtime sanitizer rails (analysis/rails.py, `SanitizerRails` gate).

The headline test is the ISSUE's transfer-guard satellite: a steady-state
SchedulingBasic drain completes under an AMBIENT
`jax.transfer_guard("disallow")` — every host↔device byte crosses either
inside a declared host-phase allow window or through the entries'
explicit `rails.stage()` device_put, so implicit transfers anywhere on
the drain path raise instead of silently eating PCIe/ICI bandwidth.
The rest covers the other three rails (retrace budget, donation
poisoning, NaN/inf guard) and the gate wiring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.analysis.rails import (GLOBAL as RAILS,
                                           RetraceBudgetExceeded,
                                           SanitizerError, SanitizerRails)
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.config import KubeSchedulerConfiguration
from kubernetes_tpu.perf.ledger import GLOBAL as LEDGER
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _cluster(nodes=8, rails=True, **kw):
    cfg = KubeSchedulerConfiguration(
        feature_gates={"SanitizerRails": rails})
    api = APIServer()
    sched = Scheduler(api, config=cfg, **kw)
    for i in range(nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": "16", "memory": "32Gi", "pods": 110})
            .zone(f"z{i % 2}")
            .label("kubernetes.io/hostname", f"n{i}").obj())
    return api, sched


def _feed(api, n, prefix="p", cpu="100m"):
    for i in range(n):
        api.create_pod(make_pod(f"{prefix}{i}")
                       .req({"cpu": cpu, "memory": "64Mi"}).obj())


@pytest.fixture()
def rails_off_after():
    """Every test leaves the process-global rails disabled (the default
    gate state) so unrelated suites never inherit an armed guard."""
    yield
    RAILS.enable(False)


class TestTransferGuardDrain:
    def test_steady_state_drain_under_ambient_disallow(self, rails_off_after):
        """ISSUE satellite: the SchedulingBasic hot path completes under
        jax.transfer_guard("disallow") with transfers confined to the
        declared phases + explicit staging — and stays on the device
        path (zero fallbacks)."""
        api, sched = _cluster(nodes=8)
        assert RAILS.active
        _feed(api, 64, prefix="warm")
        assert sched.schedule_pending() == 64   # warm: compiles + uploads
        staged_before = RAILS.staged_bytes
        _feed(api, 64, prefix="steady")
        with jax.transfer_guard("disallow"):
            bound = sched.schedule_pending()
        assert bound == 64
        assert sched.device_fallbacks == 0
        assert sched.host_scheduled == 0
        # the per-dispatch pod rows crossed via the declared escape
        assert RAILS.staged_bytes > staged_before

    def test_group_wave_drain_under_ambient_disallow(self, rails_off_after):
        """The wave path too: spread pods exercise wave_statics (whose
        lazy cache fill runs INSIDE the dispatch region — it opens the
        declared host_cache window) and the donating run_wave_scan."""
        api, sched = _cluster(nodes=8)

        def spread(name):
            return (make_pod(name).req({"cpu": "100m", "memory": "64Mi"})
                    .label("app", "web")
                    .spread_constraint(1, "topology.kubernetes.io/zone",
                                       "ScheduleAnyway", {"app": "web"})
                    .obj())

        for i in range(24):
            api.create_pod(spread(f"warm{i}"))
        assert sched.schedule_pending() == 24
        for i in range(24):
            api.create_pod(spread(f"steady{i}"))
        poisoned_before = RAILS.poisoned
        with jax.transfer_guard("disallow"):
            assert sched.schedule_pending() == 24
        assert sched.device_fallbacks == 0
        assert sched.host_scheduled == 0
        # the donating wave dispatch consumed (and poisoned) its carry
        assert RAILS.poisoned > poisoned_before

    def test_undeclared_transfer_raises_not_degrades(self, rails_off_after):
        """A violation must surface as an error, not silently fall back
        to the host oracle (which would mask the bug)."""
        api, sched = _cluster(nodes=4)
        _feed(api, 16, prefix="warm")
        sched.schedule_pending()
        _feed(api, 16)
        with jax.transfer_guard("disallow"):
            # an out-of-phase implicit upload — exactly what the rails
            # exist to catch
            with pytest.raises(Exception, match="[Dd]isallowed"):
                jnp.asarray(np.arange(1000)) + 1

    def test_gate_off_keeps_vanilla_behavior(self, rails_off_after):
        api, sched = _cluster(nodes=4, rails=False)
        assert not RAILS.active
        _feed(api, 32)
        assert sched.schedule_pending() == 32
        # no staging happened: stage() is identity when disabled
        assert RAILS.stage((np.arange(4),))[0] is not None

    def test_rails_on_matches_rails_off_assignments(self, rails_off_after):
        """Rails must observe, never steer: identical bind decisions."""

        def run(rails):
            api, sched = _cluster(nodes=6, rails=rails)
            _feed(api, 48)
            sched.schedule_pending()
            return sorted((p.metadata.name, p.spec.node_name)
                          for p in api.pods.values())

        assert run(True) == run(False)


class TestRetraceBudget:
    def test_fresh_compile_beyond_budget_raises(self, rails_off_after):
        RAILS.enable(True)
        probe = jax.jit(lambda x: x * 3)
        x = jnp.arange(7)
        with pytest.raises(RetraceBudgetExceeded) as ei:
            with RAILS.retrace_budget(0):
                LEDGER.measured_call("rails_probe_kernel", probe, x)
        assert "rails_probe_kernel" in str(ei.value)

    def test_warm_call_fits_zero_budget(self, rails_off_after):
        RAILS.enable(True)
        probe = jax.jit(lambda x: x - 1)
        x = jnp.arange(5)
        LEDGER.measured_call("rails_warm_kernel", probe, x)   # compile
        with RAILS.retrace_budget(0):
            LEDGER.measured_call("rails_warm_kernel", probe, x)

    def test_budget_scopes_to_named_kernels(self, rails_off_after):
        RAILS.enable(True)
        probe = jax.jit(lambda x: x + 11)
        x = jnp.arange(3)
        # a compile on an UNnamed kernel does not charge the budget
        with RAILS.retrace_budget(0, kernels=("some_other_kernel",)):
            LEDGER.measured_call("rails_scoped_kernel", probe, x)


class TestDonationPoisoning:
    def test_poison_deletes_input_buffers(self, rails_off_after):
        RAILS.enable(True)
        donated = (jnp.arange(16), jnp.ones((4, 4)))
        out = jnp.zeros(8)
        deleted = RAILS.poison_donated(donated, out)
        assert deleted == 2
        with pytest.raises(RuntimeError):
            np.asarray(donated[0])

    def test_output_aliased_buffers_survive(self, rails_off_after):
        RAILS.enable(True)
        a, b = jnp.arange(10), jnp.ones(6)
        # identity jit can alias: simulate by passing the SAME leaf as out
        deleted = RAILS.poison_donated((a, b), out=(a,))
        assert deleted == 1
        np.asarray(a)   # kept
        with pytest.raises(RuntimeError):
            np.asarray(b)

    def test_noop_when_disabled(self, rails_off_after):
        a = jnp.arange(4)
        assert RAILS.poison_donated((a,)) == 0
        np.asarray(a)

    def test_cpu_run_batch_poisons_consumed_carry(self, rails_off_after):
        """ops/program.py run_batch on a non-donating backend (CPU)
        poisons the input carry — use-after-donate raises HERE instead of
        corrupting state on a real accelerator."""
        api, sched = _cluster(nodes=4)
        # a run shorter than UNIFORM_RUN_MIN keeps the scan/wavescan path
        # — the donating dispatch kinds (uniform never donates)
        _feed(api, 8)
        poisoned_before = RAILS.poisoned
        assert sched.schedule_pending() == 8
        assert RAILS.poisoned > poisoned_before


class TestNanGuard:
    def test_assert_finite_raises_on_nan_and_inf(self, rails_off_after):
        RAILS.enable(True)
        with pytest.raises(SanitizerError, match="non-finite"):
            RAILS.assert_finite("probe", (jnp.array([1.0, float("nan")]),))
        with pytest.raises(SanitizerError, match="non-finite"):
            RAILS.assert_finite("probe", (jnp.array([float("inf")]),))
        RAILS.assert_finite("probe", (jnp.array([1.0, 2.0]),
                                      jnp.arange(3)))   # ints skipped

    def test_score_probe_runs_clean_on_healthy_drain(self, rails_off_after):
        """check_scores wires the score_probe kernel through a live
        drain — a healthy cluster's score surface is finite (the probe
        itself runs inside _dispatch_device_drain_inner when rails on)."""
        api, sched = _cluster(nodes=6)
        _feed(api, 32)
        assert sched.schedule_pending() == 32
        assert sched.device_fallbacks == 0
        assert "score_probe" in LEDGER.kernels   # the probe dispatched

    def test_nan_guard_scope(self, rails_off_after):
        RAILS.enable(True)
        with RAILS.nan_guard():
            _ = jnp.ones(3) + 1


@pytest.mark.slow
class TestRetraceBudgetRegression:
    """ISSUE satellite: the warm 2× re-run of EVERY bench workload must
    mint zero fresh XLA executables across the thirteen JIT entry
    kernels —
    the enforced (RetraceBudgetExceeded-raising) replacement for the
    ledger's single-cluster stability check in test_profiler.py."""

    # a workload's drain chunking varies slightly with wall-clock timing,
    # so one warm pass may miss a pow2 span bucket the next pass hits —
    # the contract is a FIXED POINT: within a few passes the (bounded)
    # bucket family is fully minted, and from then on every re-run is
    # retrace-free. A kernel that keeps minting past the cap is a real
    # retrace bomb (unbounded distinct shapes) — exactly what this gate
    # exists to catch.
    WARM_PASSES_MAX = 4

    def test_warm_rerun_of_every_bench_workload(self, rails_off_after):
        import os
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, repo)
        import bench
        from kubernetes_tpu.perf.harness import run_config
        from kubernetes_tpu.perf.ledger import KERNELS

        cfg = os.path.join(repo, "kubernetes_tpu", "perf", "configs",
                           "performance-config.yaml")
        never_stable = {}
        for case, _big, small_wl, _threshold in bench.CASES:
            for _ in range(self.WARM_PASSES_MAX):
                before = {k: r.compiles for k, r in LEDGER.kernels.items()}
                run_config(cfg, case, small_wl)
                deltas = {k: r.compiles - before.get(k, 0)
                          for k, r in LEDGER.kernels.items()
                          if k in KERNELS and r.compiles - before.get(k, 0)}
                if not deltas:
                    break
            else:
                never_stable[case] = deltas
                continue
            # the fixed point must HOLD: the next full re-run fits a zero
            # retrace budget across all thirteen entry kernels (raises
            # RetraceBudgetExceeded otherwise)
            with RAILS.retrace_budget(0, kernels=KERNELS):
                run_config(cfg, case, small_wl)
        assert not never_stable, (
            f"kernels still minting after {self.WARM_PASSES_MAX} warm "
            f"passes: {never_stable}")


class TestGateWiring:
    def test_scheduler_gate_toggles_global(self, rails_off_after):
        _cluster(rails=True)
        assert RAILS.active
        _cluster(rails=False)
        assert not RAILS.active

    def test_unknown_gate_name_rejected(self):
        with pytest.raises(Exception):
            KubeSchedulerConfiguration(
                feature_gates={"SanitizerRailz": True}).validate()

    def test_scoped_enable_restores(self):
        local = SanitizerRails()
        assert not local.active
        with local.enabled(True):
            assert local.active
        assert not local.active
