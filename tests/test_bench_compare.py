"""Bench regression sentinel (tools/bench_compare.py, ISSUE 5).

Fast self-tests: the real r04→r05 pair passes, an injected 20%
SchedulingBasic regression is flagged (module-level and via the CLI exit
code), both bench JSON formats normalize. The slow test runs
`bench_compare.py --check` against a FRESH bench — the trajectory as an
enforced contract rather than archaeology.
"""

import copy
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "bench_compare.py")

_spec = importlib.util.spec_from_file_location("bench_compare", TOOL)
bench_compare = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_compare)


def _load(name):
    with open(os.path.join(REPO, name)) as f:
        return json.load(f)


def _has_trail():
    return (os.path.exists(os.path.join(REPO, "BENCH_r04.json"))
            and os.path.exists(os.path.join(REPO, "BENCH_r05.json")))


class TestNormalize:
    def test_legacy_headline_plus_extra(self):
        payload = {"parsed": {
            "metric": "SchedulingBasic_5000_throughput", "value": 100.0,
            "unit": "pods/s",
            "extra": {
                "TopologySpreading_5000": {"value": 50.0, "p50": 55,
                                           "p99": 60,
                                           "attempt_p99_ms": 2.0},
                "Sharded_8dev": {"pods_per_s": 99.0},   # no "value": skip
            }}}
        s = bench_compare.normalize(payload)
        assert s["SchedulingBasic_5000"]["pods_per_s"] == 100.0
        assert s["TopologySpreading_5000"]["attempt_p99_ms"] == 2.0
        assert "Sharded_8dev" not in s

    def test_new_summary_block_wins(self):
        payload = {"summary": {"A": {"pods_per_s": 10.0, "p50": 1,
                                     "p99": 2}},
                   "metric": "B_throughput", "value": 999.0}
        assert set(bench_compare.normalize(payload)) == {"A"}

    def test_garbage_rejected(self):
        with pytest.raises(ValueError):
            bench_compare.normalize({"nothing": True})


class TestCompare:
    def test_drop_within_noise_passes(self):
        base = {"TopologySpreading_x": {"pods_per_s": 100.0}}
        new = {"TopologySpreading_x": {"pods_per_s": 80.0}}   # -20% < 30%
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_throughput_drop_fails_default_gate(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 89.0}}
        failures, _ = bench_compare.compare(base, new)
        assert any("THROUGHPUT" in f for f in failures)

    def test_p99_growth_fails(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "attempt_p99_ms": 10.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "attempt_p99_ms": 13.0}}
        failures, _ = bench_compare.compare(base, new)
        assert any("P99" in f for f in failures)

    def test_p99_skipped_when_absent(self):
        base = {"A_x": {"pods_per_s": 100.0}}
        new = {"A_x": {"pods_per_s": 100.0, "attempt_p99_ms": 99.0}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_disjoint_workloads_fail_loudly(self):
        failures, _ = bench_compare.compare(
            {"A_x": {"pods_per_s": 1.0}}, {"B_x": {"pods_per_s": 1.0}})
        assert any("no shared workloads" in f for f in failures)

    def test_host_share_regression_fails(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "host_share": 0.40}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "host_share": 0.47}}   # +17.5% rel
        failures, _ = bench_compare.compare(base, new)
        assert any("HOST PHASE SHARE" in f for f in failures)

    def test_host_share_within_gate_passes(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "host_share": 0.40}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "host_share": 0.43}}   # +7.5% rel
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_host_share_skipped_when_baseline_predates_field(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "host_share": 0.99}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_sharded_probe_excluded(self):
        base = {"Sharded_8dev": {"pods_per_s": 100.0},
                "A_x": {"pods_per_s": 100.0}}
        new = {"Sharded_8dev": {"pods_per_s": 1.0},
               "A_x": {"pods_per_s": 100.0}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures


@pytest.mark.skipif(not _has_trail(), reason="BENCH_r04/r05 not present")
class TestRealTrail:
    def test_r04_to_r05_pair_passes(self):
        base = bench_compare.normalize(_load("BENCH_r04.json"))
        new = bench_compare.normalize(_load("BENCH_r05.json"))
        failures, report = bench_compare.compare(base, new)
        assert not failures, failures
        assert report

    def test_injected_20pct_regression_flagged(self, tmp_path):
        """The acceptance gate: a copied BENCH json with SchedulingBasic
        scaled to 80% must trip the sentinel (module AND cli)."""
        doc = copy.deepcopy(_load("BENCH_r05.json"))
        doc["parsed"]["value"] = round(doc["parsed"]["value"] * 0.8, 1)
        injected = tmp_path / "injected.json"
        injected.write_text(json.dumps(doc))

        failures, _ = bench_compare.compare(
            bench_compare.normalize(_load("BENCH_r05.json")),
            bench_compare.normalize(doc))
        assert any("THROUGHPUT" in f and "SchedulingBasic" in f
                   for f in failures)

        out = subprocess.run(
            [sys.executable, TOOL, "--baseline",
             os.path.join(REPO, "BENCH_r05.json"), "--new", str(injected)],
            capture_output=True, text=True)
        assert out.returncode == 2
        assert "SENTINEL: FAIL" in out.stdout

    def test_cli_green_exit_zero(self):
        out = subprocess.run(
            [sys.executable, TOOL, "--baseline",
             os.path.join(REPO, "BENCH_r04.json"), "--new",
             os.path.join(REPO, "BENCH_r05.json")],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "SENTINEL: OK" in out.stdout


class TestE2EGate:
    """Queue→bind e2e latency gate (ISSUE 13): >25% e2e_p99_ms growth
    trips the sentinel; the field is skipped when either side predates
    it (0.0 — the seeded value before any observation)."""

    def test_e2e_growth_beyond_gate_fails(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "e2e_p99_ms": 40.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "e2e_p99_ms": 52.0}}   # +30% > 25%
        failures, _ = bench_compare.compare(base, new)
        assert any("E2E LATENCY REGRESSION" in f for f in failures)

    def test_e2e_growth_within_gate_passes(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "e2e_p99_ms": 40.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "e2e_p99_ms": 47.0}}   # +17.5%
        failures, report = bench_compare.compare(base, new)
        assert not failures
        assert any("queue->bind e2e p99" in ln for ln in report)

    def test_e2e_skipped_when_baseline_predates_field(self):
        base = {"SchedulingBasic_x": {"pods_per_s": 100.0}}
        new = {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                     "e2e_p99_ms": 500.0}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_cli_synthetic_e2e_regression_flips_exit_code(self, tmp_path):
        """End-to-end self-test: a copied summary with e2e_p99_ms scaled
        ×1.5 must trip the sentinel through the CLI, and the unscaled
        pair must pass."""
        base = {"summary": {"SchedulingBasic_X": {
            "pods_per_s": 1000.0, "p50": 900, "p99": 1100,
            "attempt_p50_ms": 1.0, "attempt_p99_ms": 2.0,
            "e2e_p50_ms": 12.0, "e2e_p99_ms": 40.0}}}
        bad_doc = copy.deepcopy(base)
        bad_doc["summary"]["SchedulingBasic_X"]["e2e_p99_ms"] = 60.0
        bp = tmp_path / "base.json"
        gp = tmp_path / "good.json"
        rp = tmp_path / "regressed.json"
        bp.write_text(json.dumps(base))
        gp.write_text(json.dumps(base))
        rp.write_text(json.dumps(bad_doc))
        ok = subprocess.run(
            [sys.executable, TOOL, "--baseline", str(bp), "--new",
             str(gp)], capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = subprocess.run(
            [sys.executable, TOOL, "--baseline", str(bp), "--new",
             str(rp)], capture_output=True, text=True)
        assert bad.returncode == 2
        assert "E2E LATENCY REGRESSION" in bad.stdout
        assert "SENTINEL: FAIL" in bad.stdout


class TestKernelGate:
    """Per-kernel device-time gate (ISSUE 14): one JIT entry's p99
    growing >30% trips the sentinel even when throughput holds; kernels
    absent on either side (older BENCH files, undisplayed kernels) and
    sub-bucket jitter are skipped."""

    @staticmethod
    def _wl(kernels):
        return {"SchedulingBasic_x": {"pods_per_s": 100.0,
                                      "kernels": kernels}}

    def test_kernel_p99_growth_beyond_gate_fails(self):
        base = self._wl({"run_batch": {"seconds": 1.0, "p99_ms": 10.0}})
        new = self._wl({"run_batch": {"seconds": 1.4, "p99_ms": 14.0}})
        failures, _ = bench_compare.compare(base, new)
        assert any("KERNEL P99 REGRESSION" in f and "run_batch" in f
                   for f in failures)

    def test_kernel_p99_within_gate_passes(self):
        base = self._wl({"run_batch": {"p99_ms": 10.0}})
        new = self._wl({"run_batch": {"p99_ms": 12.0}})   # +20% < 30%
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_kernel_skipped_when_absent_on_either_side(self):
        base = self._wl({})
        new = self._wl({"run_wave": {"p99_ms": 99.0}})
        failures, _ = bench_compare.compare(base, new)
        assert not failures
        failures, _ = bench_compare.compare(new, self._wl({}))
        assert not failures

    def test_sub_bucket_jitter_never_gates(self):
        # +100% relative but only 0.02ms absolute: log2 bucket noise
        base = self._wl({"scatter_rows": {"p99_ms": 0.02}})
        new = self._wl({"scatter_rows": {"p99_ms": 0.04}})
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_cli_synthetic_kernel_regression_flips_exit_code(
            self, tmp_path):
        """End-to-end self-test: scale ONE kernel's p99 ×1.5 in a copied
        summary — the sentinel must exit 2; the unscaled pair passes."""
        base = {"summary": {"SchedulingBasic_X": {
            "pods_per_s": 1000.0, "p50": 900, "p99": 1100,
            "kernels": {"run_uniform": {"calls": 50, "seconds": 2.0,
                                        "p50_ms": 20.0, "p99_ms": 40.0},
                        "run_batch": {"calls": 5, "seconds": 0.1,
                                      "p50_ms": 10.0, "p99_ms": 20.0}}}}}
        bad_doc = copy.deepcopy(base)
        bad_doc["summary"]["SchedulingBasic_X"]["kernels"][
            "run_uniform"]["p99_ms"] = 60.0
        bp = tmp_path / "base.json"
        gp = tmp_path / "good.json"
        rp = tmp_path / "regressed.json"
        bp.write_text(json.dumps(base))
        gp.write_text(json.dumps(base))
        rp.write_text(json.dumps(bad_doc))
        ok = subprocess.run(
            [sys.executable, TOOL, "--baseline", str(bp), "--new",
             str(gp)], capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = subprocess.run(
            [sys.executable, TOOL, "--baseline", str(bp), "--new",
             str(rp)], capture_output=True, text=True)
        assert bad.returncode == 2
        assert "KERNEL P99 REGRESSION" in bad.stdout
        assert "run_uniform" in bad.stdout
        assert "SENTINEL: FAIL" in bad.stdout


class TestStreamingGate:
    """Streaming tiers (ISSUE 18): the Streaming* prefixes run the wide
    noise gate; the overlap floor fails only when a pipeline-mode
    workload loses occupancy the baseline held; the delta-e2e numbers
    ride the ordinary e2e gate at the same offered load."""

    def test_streaming_prefix_gets_wide_noise_gate(self):
        assert bench_compare.throughput_gate(
            "StreamingBasic_5000Nodes_20kQPS_pipeline") == 0.30
        assert bench_compare.throughput_gate(
            "StreamingSharded_5000Nodes") == 0.30
        base = {"StreamingBasic_x_pipeline": {"pods_per_s": 100.0}}
        new = {"StreamingBasic_x_pipeline": {"pods_per_s": 75.0}}  # -25%
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_occupancy_floor_lost_fails(self):
        base = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 1.45}}}
        new = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 1.05}}}
        failures, _ = bench_compare.compare(base, new)
        assert any("PIPELINE OVERLAP REGRESSION" in f for f in failures)

    def test_occupancy_above_floor_passes_and_reports(self):
        base = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 1.45}}}
        new = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 1.31}}}
        failures, report = bench_compare.compare(base, new)
        assert not failures
        assert any("stage occupancy" in ln for ln in report)

    def test_occupancy_skipped_when_baseline_below_floor(self):
        """A baseline recorded on a loaded machine (occupancy < 1.2)
        cannot make every future run unreproducible."""
        base = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 1.1}}}
        new = {"StreamingBasic_x_pipeline": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "pipeline", "occupancy": 0.9}}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_lockstep_mode_never_gated_on_occupancy(self):
        base = {"StreamingBasic_x_lockstep": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "lockstep", "occupancy": 1.5}}}
        new = {"StreamingBasic_x_lockstep": {
            "pods_per_s": 100.0,
            "pipeline": {"mode": "lockstep", "occupancy": 0.5}}}
        failures, _ = bench_compare.compare(base, new)
        assert not failures

    def test_streaming_e2e_rides_the_same_offered_load_gate(self):
        """Same workload name = same qps tier: the delta-e2e p99 gates
        like any other e2e_p99_ms field."""
        base = {"StreamingBasic_x_pipeline": {"pods_per_s": 100.0,
                                              "e2e_p99_ms": 40.0}}
        new = {"StreamingBasic_x_pipeline": {"pods_per_s": 100.0,
                                             "e2e_p99_ms": 52.0}}
        failures, _ = bench_compare.compare(base, new)
        assert any("E2E LATENCY REGRESSION" in f for f in failures)


class TestSLOGate:
    """--slo (ISSUE 10): burn-rate breaches and shadow-oracle divergence
    recorded in a bench summary fail the sentinel."""

    def _summary(self, slo):
        return {"SchedulingBasic_X": {
            "pods_per_s": 1000.0, "p50": 900, "p99": 1100,
            "attempt_p50_ms": 1.0, "attempt_p99_ms": 2.0, "slo": slo}}

    def test_clean_slo_passes(self):
        assert bench_compare.slo_failures(self._summary(
            {"breaches": [], "divergence_total": 0})) == []

    def test_synthetic_breach_fails(self):
        fails = bench_compare.slo_failures(self._summary(
            {"breaches": [{"sli": "attempt_latency", "window": "5m",
                           "burn": 20.0, "threshold": 14.4}],
             "divergence_total": 0}))
        assert fails and "SLO BREACH" in fails[0]

    def test_nonzero_divergence_fails(self):
        fails = bench_compare.slo_failures(self._summary(
            {"breaches": [], "divergence_total": 2}))
        assert fails and "ORACLE DIVERGENCE" in fails[0]

    def test_cli_slo_gate_fast_selftest(self, tmp_path):
        """End-to-end: inject a synthetic breach into a copied summary
        and prove --slo flips the exit code while the plain run passes."""
        base = {"summary": self._summary(
            {"breaches": [], "divergence_total": 0})}
        breach = copy.deepcopy(base)
        breach["summary"]["SchedulingBasic_X"]["slo"] = {
            "breaches": [{"sli": "divergence", "window": "6h",
                          "burn": 100.0, "threshold": 1.0}],
            "divergence_total": 1}
        bp = tmp_path / "base.json"
        np_ = tmp_path / "new.json"
        bp.write_text(json.dumps(base))
        np_.write_text(json.dumps(breach))
        ok = subprocess.run(
            [sys.executable, TOOL, "--baseline", str(bp), "--new",
             str(np_)], capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = subprocess.run(
            [sys.executable, TOOL, "--slo", "--baseline", str(bp),
             "--new", str(np_)], capture_output=True, text=True)
        assert bad.returncode == 2
        assert "SLO BREACH" in bad.stdout
        assert "ORACLE DIVERGENCE" in bad.stdout


class TestStitchGate:
    """ISSUE 19: orphaned journey fragments and stitch gaps in the
    `shard` proof block fail the sentinel like double-binds do."""

    def _summary(self, shard):
        return {"MultiShardBasic_X": {
            "pods_per_s": 400.0, "p50": 390, "p99": 410,
            "attempt_p50_ms": 1.0, "attempt_p99_ms": 2.0,
            "shard": shard}}

    def test_fully_stitched_passes(self):
        assert bench_compare.slo_failures(self._summary(
            {"double_binds": 0, "divergence": 0, "ledgers_verified": True,
             "orphaned_fragments": 0, "journeys_total": 4096,
             "journeys_stitched": 4096})) == []

    def test_orphaned_fragments_fail(self):
        fails = bench_compare.slo_failures(self._summary(
            {"double_binds": 0, "divergence": 0, "ledgers_verified": True,
             "orphaned_fragments": 3, "journeys_total": 8,
             "journeys_stitched": 6}))
        assert any(f.startswith("ORPHANED JOURNEY") for f in fails)
        assert any(f.startswith("JOURNEY STITCH GAP") for f in fails)

    def test_pre19_payload_without_stitch_block_passes(self):
        assert bench_compare.slo_failures(self._summary(
            {"double_binds": 0, "divergence": 0,
             "ledgers_verified": True})) == []


class TestEnvFingerprint:
    """ISSUE 19: cross-container throughput comparisons downgrade to
    warnings when the env fingerprints differ; everything else (and
    unstamped payloads) stays strict."""

    ENV_A = {"cpu_model": "Xeon 8481C", "cpu_count": 16,
             "versions": {"python": "3.11.8", "jax": "0.4.30"},
             "jax_platforms": "cpu"}

    def test_mismatch_fields(self):
        env_b = dict(self.ENV_A, cpu_model="EPYC 9B14", cpu_count=8)
        assert bench_compare.fingerprint_mismatch(self.ENV_A, env_b) \
            == ["cpu_model", "cpu_count"]
        assert bench_compare.fingerprint_mismatch(
            self.ENV_A, dict(self.ENV_A)) == []

    def test_absent_stamp_stays_strict(self):
        assert bench_compare.fingerprint_mismatch({}, self.ENV_A) == []
        assert bench_compare.fingerprint_mismatch(self.ENV_A, {}) == []

    def test_env_fingerprint_reads_both_payload_shapes(self):
        assert bench_compare.env_fingerprint(
            {"env": self.ENV_A}) == self.ENV_A
        assert bench_compare.env_fingerprint(
            {"parsed": {"env": self.ENV_A}}) == self.ENV_A
        assert bench_compare.env_fingerprint({"summary": {}}) == {}

    def test_cli_cross_container_throughput_downgrades(self, tmp_path):
        """A 2× throughput drop between DIFFERENT containers warns (exit
        0, WARNING line); the same drop with matching fingerprints — or
        with no fingerprints at all — still fails (exit 2)."""
        wl = {"pods_per_s": 1000.0, "p50": 900, "p99": 1100,
              "attempt_p50_ms": 1.0, "attempt_p99_ms": 2.0}
        slow_wl = dict(wl, pods_per_s=500.0, p50=450, p99=550)
        base = {"summary": {"SchedulingBasic_X": wl}, "env": self.ENV_A}
        slow_other_env = {"summary": {"SchedulingBasic_X": slow_wl},
                          "env": dict(self.ENV_A, cpu_model="EPYC 9B14")}
        slow_same_env = {"summary": {"SchedulingBasic_X": slow_wl},
                         "env": dict(self.ENV_A)}

        def run(b, n):
            bp = tmp_path / "b.json"
            np_ = tmp_path / "n.json"
            bp.write_text(json.dumps(b))
            np_.write_text(json.dumps(n))
            return subprocess.run(
                [sys.executable, TOOL, "--baseline", str(bp), "--new",
                 str(np_)], capture_output=True, text=True)

        warned = run(base, slow_other_env)
        assert warned.returncode == 0, warned.stdout + warned.stderr
        assert "WARNING (env fingerprint differs" in warned.stdout
        strict = run(base, slow_same_env)
        assert strict.returncode == 2
        assert "THROUGHPUT REGRESSION" in strict.stdout
        unstamped = run({"summary": {"SchedulingBasic_X": wl}},
                        {"summary": {"SchedulingBasic_X": slow_wl}})
        assert unstamped.returncode == 2


@pytest.mark.slow
@pytest.mark.skipif(not _has_trail(), reason="BENCH_r04/r05 not present")
class TestFreshBenchCheck:
    def test_check_fresh_schedulingbasic_vs_latest(self):
        """`bench_compare --check --cases SchedulingBasic`: a fresh bench
        run must not regress the latest BENCH_r* SchedulingBasic number
        beyond the noise gate."""
        out = subprocess.run(
            [sys.executable, TOOL, "--check", "--cases", "SchedulingBasic"],
            capture_output=True, text=True, cwd=REPO, timeout=1800)
        assert out.returncode == 0, (
            f"sentinel tripped on a fresh bench:\n{out.stdout}\n{out.stderr}")
        assert "SENTINEL: OK" in out.stdout
