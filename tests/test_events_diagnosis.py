"""Event recorder + mask-derived failure diagnosis: parity + wiring.

The core gate: FailedScheduling events built from the device filter-mask
reduction (ops/program.py diagnose_row) must BYTE-MATCH a host-oracle
filter replay — message, per-node statuses and per-plugin rejected-node
counts — on seeded unschedulable scenarios up to 5k nodes, and events
must keep firing when the device tier degrades to the host path.
"""

import pytest

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.events import (EventRecorder, FlightRecorder,
                                   REASON_FAILED_SCHEDULING,
                                   REASON_SCHEDULED)
from kubernetes_tpu.framework.interface import Code, CycleState
from kubernetes_tpu.framework.types import Diagnosis, FitError
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def host_oracle_fit_error(sched: Scheduler, pod) -> FitError:
    """The host oracle's filter replay over the live snapshot — the truth
    the device reduction must reproduce byte for byte."""
    fwk = sched.profiles[pod.spec.scheduler_name].framework
    sched.cache.update_snapshot(sched.snapshot)
    nodes = sched.snapshot.node_info_list
    diag = Diagnosis()
    state = CycleState()
    pre, status = fwk.run_pre_filter_plugins(state, pod, nodes)
    if not status.is_success():
        diag.pre_filter_msg = "; ".join(status.reasons)
        if status.plugin:
            diag.unschedulable_plugins.add(status.plugin)
    else:
        fwk.find_nodes_that_pass_filters(state, pod, nodes, pre, diag)
    err = FitError(pod, len(nodes))
    err.diagnosis = diag
    return err


def assert_device_matches_oracle(sched: Scheduler, pod) -> FitError:
    """FailedScheduling event message + the full per-node status map of
    the device diagnosis must equal the host replay's."""
    events = sched.events.events(reason=REASON_FAILED_SCHEDULING,
                                 object_ref=pod.uid)
    assert events, f"no FailedScheduling event for {pod.uid}"
    oracle = host_oracle_fit_error(sched, pod)
    assert events[-1].message == str(oracle)
    # the diagnosis the failure handler saw (per-node parity, not just the
    # aggregated message): replay the scheduler-side path
    dev = sched._device_fit_error(
        _qpi_of(sched, pod), sched.profiles[pod.spec.scheduler_name], {})
    dev_map = {n: (s.code, s.plugin, tuple(s.reasons))
               for n, s in dev.diagnosis.node_to_status.items()}
    host_map = {n: (s.code, s.plugin, tuple(s.reasons))
                for n, s in oracle.diagnosis.node_to_status.items()}
    assert dev_map == host_map
    assert (dev.diagnosis.plugin_node_counts()
            == oracle.diagnosis.plugin_node_counts())
    assert (dev.diagnosis.unschedulable_plugins
            == oracle.diagnosis.unschedulable_plugins)
    return dev


def _qpi_of(sched: Scheduler, pod):
    from kubernetes_tpu.framework.types import PodInfo, QueuedPodInfo
    return QueuedPodInfo(pod_info=PodInfo.of(pod))


def _big():
    return {"cpu": 64, "memory": "64Gi", "pods": 110}


class TestMaskDiagnosisParity:
    def test_mixed_rejections_5k_nodes(self):
        """The headline parity gate: 5000 nodes rejecting one signature
        for six different reasons (two distinct taints among them); the
        device mask-derived message must byte-match the host replay."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(5000):
            n = make_node(f"n{i:04d}").label("disk", "ssd")
            if i < 1000:
                n = n.capacity({"cpu": 4, "memory": "64Gi", "pods": 110})
            elif i < 2000:
                n = n.capacity(_big()).unschedulable()
            elif i < 2500:
                n = n.capacity(_big()).taint("dedicated", "gpu")
            elif i < 3000:
                n = n.capacity(_big()).taint("team", "infra")
            elif i < 4000:
                n = make_node(f"n{i:04d}").capacity(_big())  # no disk label
            elif i < 4500:
                n = n.capacity({"cpu": 16, "memory": "2Gi", "pods": 110})
            else:
                n = n.capacity({"cpu": 64, "memory": "64Gi", "pods": 0})
            api.create_node(n.obj())
        sched.prime()
        pod = (make_pod("p0").req({"cpu": "8", "memory": "4Gi"})
               .node_selector({"disk": "ssd"}).obj())
        api.create_pod(pod)
        assert sched.schedule_pending() == 0
        dev = assert_device_matches_oracle(sched, pod)
        counts = dev.diagnosis.plugin_node_counts()
        assert counts == {"NodeResourcesFit": 2000, "NodeUnschedulable": 1000,
                          "TaintToleration": 1000, "NodeAffinity": 1000}
        msg = str(dev)
        assert msg.startswith("0/5000 nodes are available: ")
        assert "1000 Insufficient cpu" in msg
        assert "500 node(s) had untolerated taint {dedicated: gpu}" in msg
        assert "500 node(s) had untolerated taint {team: infra}" in msg
        assert "500 Too many pods" in msg
        # per-plugin rejected-node counts land in the histogram
        m = sched.metrics.unschedulable_nodes
        assert m.count("NodeResourcesFit") >= 1
        assert m.sum("NodeResourcesFit") >= 2000

    def test_spread_skew_and_missing_label(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(2):
            api.create_node(make_node(f"a{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 20}).zone("z0").obj())
        for i in range(2):
            api.create_node(make_node(f"b{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 20}).zone("z1").obj())
        for i in range(2):
            api.create_node(make_node(f"c{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        for i in range(4):   # existing app=x pods crowd z0
            api.create_pod(make_pod(f"ex{i}").req({"cpu": "100m"})
                           .label("app", "x").node(f"a{i % 2}").obj())
        pod = (make_pod("sp").req({"cpu": "2", "memory": "1Gi"})
               .label("app", "x")
               .spread_constraint(1, ZONE, "DoNotSchedule",
                                  {"app": "x"}).obj())
        api.create_pod(pod)
        assert sched.schedule_pending() == 0
        dev = assert_device_matches_oracle(sched, pod)
        hist = dev.diagnosis.reasons_histogram()
        assert hist[
            "node(s) didn't match pod topology spread constraints"] == 2
        assert hist["node(s) didn't match pod topology spread constraints "
                    "(missing required label)"] == 2
        # missing topology label is UnschedulableAndUnresolvable
        codes = {n: s.code for n, s in dev.diagnosis.node_to_status.items()}
        assert codes["c0"] == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
        assert codes["a0"] == Code.UNSCHEDULABLE

    def test_incoming_and_existing_anti_affinity(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        for i in range(2):
            api.create_node(make_node(f"a{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 20}).zone("z0").obj())
        for i in range(2):
            api.create_node(make_node(f"b{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 20}).zone("z1").obj())
        api.create_pod(make_pod("exy").req({"cpu": "100m"})
                       .label("app", "y").node("a0").obj())
        pod = (make_pod("anti").req({"cpu": "2", "memory": "1Gi"})
               .pod_affinity(ZONE, {"app": "y"}, anti=True).obj())
        api.create_pod(pod)
        assert sched.schedule_pending() == 0
        assert_device_matches_oracle(sched, pod)

        api2 = APIServer()
        sched2 = Scheduler(api2, batch_size=64)
        for i in range(2):
            api2.create_node(make_node(f"a{i}").capacity(
                {"cpu": 8, "memory": "16Gi", "pods": 20}).zone("z0").obj())
        for i in range(2):
            api2.create_node(make_node(f"b{i}").capacity(
                {"cpu": 1, "memory": "16Gi", "pods": 20}).zone("z1").obj())
        api2.create_pod(make_pod("guard").req({"cpu": "100m"})
                        .label("app", "g")
                        .pod_affinity(ZONE, {"app": "z"}, anti=True)
                        .node("a0").obj())
        pod2 = (make_pod("victim").req({"cpu": "2", "memory": "1Gi"})
                .label("app", "z").obj())
        api2.create_pod(pod2)
        assert sched2.schedule_pending() == 0
        dev = assert_device_matches_oracle(sched2, pod2)
        assert ("node(s) didn't satisfy existing pods anti-affinity rules"
                in dev.diagnosis.reasons_histogram())

    def test_host_port_signature(self):
        """Host-port pods carry sig 0 yet still get the mask diagnosis
        (their table row exists; ports come from the snapshot carry)."""
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("p0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        api.create_node(make_node("p1").capacity(
            {"cpu": 1, "memory": "16Gi", "pods": 20}).obj())
        api.create_pod(make_pod("web").req({"cpu": "100m"})
                       .host_port(8080).node("p0").obj())
        pod = (make_pod("web2").req({"cpu": "2", "memory": "1Gi"})
               .host_port(8080).obj())
        api.create_pod(pod)
        assert sched.schedule_pending() == 0
        dev = assert_device_matches_oracle(sched, pod)
        hist = dev.diagnosis.reasons_histogram()
        assert hist["node(s) didn't have free ports for the requested "
                    "pod ports"] == 1

    def test_gate_off_uses_host_replay_with_identical_result(self):
        def build(gates):
            api = APIServer()
            from kubernetes_tpu.config import KubeSchedulerConfiguration
            cfg = KubeSchedulerConfiguration(feature_gates=gates)
            sched = Scheduler(api, batch_size=64, config=cfg)
            for i in range(4):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 2, "memory": "4Gi", "pods": 10}).obj())
            pod = make_pod("p").req({"cpu": "8", "memory": "1Gi"}).obj()
            api.create_pod(pod)
            sched.schedule_pending()
            return sched.events.events(
                reason=REASON_FAILED_SCHEDULING)[-1].message
        on = build({})
        off = build({"DeviceMaskDiagnosis": False})
        assert on == off
        assert "4 Insufficient cpu" in on


class TestEventsAcrossFallback:
    def test_events_fire_on_device_fault_fallback(self, monkeypatch):
        """Chaos: the device tier faults, the drain degrades to the host
        oracle — Scheduled AND FailedScheduling events must still fire."""
        import kubernetes_tpu.scheduler as sched_mod

        def boom(*a, **k):
            raise RuntimeError("injected XLA fault")

        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 4, "memory": "8Gi", "pods": 10}).obj())
        monkeypatch.setattr(sched_mod, "run_batch", boom)
        monkeypatch.setattr(sched_mod, "run_uniform", boom)
        api.create_pod(make_pod("ok").req(
            {"cpu": "1", "memory": "1Gi"}).obj())
        api.create_pod(make_pod("big").req(
            {"cpu": "100", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 1
        assert sched.device_fallbacks >= 1
        ok_ev = sched.events.events(reason=REASON_SCHEDULED,
                                    object_ref="default/ok")
        assert ok_ev and "to n0" in ok_ev[-1].message
        fail_ev = sched.events.events(reason=REASON_FAILED_SCHEDULING,
                                      object_ref="default/big")
        assert fail_ev and "Insufficient cpu" in fail_ev[-1].message
        # the fault itself is in the flight ring
        faults = [r for r in sched.flight.dump() if r["fallback"]]
        assert faults and faults[0]["fallback"] == "dispatch"


class TestEventRecorder:
    def test_aggregation_and_counts(self):
        clock = iter(range(100)).__next__
        rec = EventRecorder(capacity=8, clock=lambda: float(clock()))
        for _ in range(3):
            rec.event("default/p", "Warning", "FailedScheduling",
                      "0/1 nodes are available: 1 Insufficient cpu.")
        evs = rec.events(reason="FailedScheduling")
        assert len(evs) == 1 and evs[0].count == 3
        assert evs[0].first_timestamp < evs[0].last_timestamp
        assert rec.counts[("Warning", "FailedScheduling")] == 3

    def test_ring_eviction(self):
        rec = EventRecorder(capacity=4)
        for i in range(8):
            rec.event(f"default/p{i}", "Warning", "FailedScheduling", "m")
        evs = rec.events(reason="FailedScheduling")
        assert len(evs) == 4
        assert {e.object_ref for e in evs} == {f"default/p{i}"
                                               for i in range(4, 8)}

    def test_scheduled_fast_path_renders_reference_message(self):
        rec = EventRecorder()
        rec.scheduled("default/p", "node-3")
        rec.scheduled_bulk([("default/q", "node-4")])
        evs = rec.events(reason="Scheduled")
        assert [e.message for e in evs] == [
            "Successfully assigned default/p to node-3",
            "Successfully assigned default/q to node-4"]
        dump = rec.dump()
        assert dump["counts"] == {"Normal/Scheduled": 2}

    def test_metrics_series_increment(self):
        from kubernetes_tpu.metrics import SchedulerMetrics
        m = SchedulerMetrics()
        rec = EventRecorder(metrics=m)
        rec.scheduled("default/p", "n0")
        rec.event("default/q", "Warning", "FailedScheduling", "no")
        assert m.events_total.value("Normal", "Scheduled") == 1
        assert m.events_total.value("Warning", "FailedScheduling") == 1


class TestFlightRecorder:
    def test_ring_and_slowest(self):
        fr = FlightRecorder(capacity=4)
        for i in range(6):
            fr.record(profile="default-scheduler", pods=64, bound=60,
                      failed=4, signatures=2, kinds=("scan",), groups=False,
                      phases={"host_build": float(i)})
        records = fr.dump()
        assert len(records) == 4
        assert records[-1]["seq"] == 6
        assert fr.slowest(1)[0]["phases"]["host_build"] == 5.0
        assert fr.dump(limit=2)[0]["seq"] == 5

    def test_scheduler_records_drains(self):
        api = APIServer()
        sched = Scheduler(api, batch_size=64)
        api.create_node(make_node("n0").capacity(
            {"cpu": 8, "memory": "16Gi", "pods": 20}).obj())
        for i in range(4):
            api.create_pod(make_pod(f"p{i}").req(
                {"cpu": "1", "memory": "1Gi"}).obj())
        assert sched.schedule_pending() == 4
        records = sched.flight.dump()
        assert records
        rec = records[-1]
        assert rec["pods"] == 4 and rec["bound"] == 4
        assert rec["signatures"] == 1
        assert rec["kinds"]
        # the phase map carries the decomposed host_build
        for phase in ("host_build", "host_tensorize", "host_group_seed",
                      "host_cache", "device_dispatch", "commit"):
            assert phase in rec["phases"], phase
