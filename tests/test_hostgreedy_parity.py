"""HostGreedy ↔ device-scan parity (ops/hostgreedy.py vs ops/program.py).

The host greedy is the fast path for same-signature group runs; its
contract is BIT-IDENTICAL assignments to the device scan (which is itself
oracle-verified in test_groups_parity.py). The fuzz feeds both paths the
same pre-populated clusters and identical pod runs across every group
constraint family.
"""

import random

import numpy as np

from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.backend.cache import Cache, Snapshot
from kubernetes_tpu.ops.groups import to_device
from kubernetes_tpu.ops.hostgreedy import HostGreedy
from kubernetes_tpu.ops.program import (ScoreConfig, initial_carry,
                                        pod_rows_from_batch, run_batch)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.state.batch import BatchBuilder
from kubernetes_tpu.state.tensorize import ClusterState
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


def scan_vs_greedy(nodes, existing, batch_pods, cfg=ScoreConfig()):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for pod, node_name in existing:
        pod.spec.node_name = node_name
        cache.add_pod(pod)
    snap = Snapshot()
    cache.update_snapshot(snap)

    state = ClusterState()
    state.apply_snapshot(snap, full=True)
    builder = BatchBuilder(state)
    batch = builder.build(batch_pods)
    assert not batch.host_fallback.any()
    sig = batch.sig[:len(batch_pods)]
    assert (sig == sig[0]).all() and sig[0] != 0, "fuzz needs one signature"

    gd_np, gc_np = builder.groups.build_dev(snap)
    # scan
    gd, gc = to_device(gd_np), to_device(gc_np)
    na = state.device_arrays()
    xs, table = pod_rows_from_batch(batch)
    _, scan_out = run_batch(cfg, na, initial_carry(na, gc), xs, table,
                            groups=gd)
    scan_out = np.asarray(scan_out)[:len(batch_pods)]
    # greedy — n_eff exercises the production node-axis slicing whenever
    # the live node count is below the pow2 bucket
    hg = HostGreedy(cfg, state.ensure_arrays(), builder.table,
                    int(batch.tidx[0]), gd_np, gc_np,
                    n_eff=len(state.node_names))
    assert hg.ok
    greedy_out = hg.run(len(batch_pods))
    assert (scan_out == greedy_out).all(), (scan_out.tolist(),
                                            greedy_out.tolist())
    return greedy_out


def _nodes(n, zones, cpu=16, seed_caps=None):
    out = []
    for i in range(n):
        cap = cpu if seed_caps is None else seed_caps[i]
        out.append(make_node(f"n{i}")
                   .capacity({"cpu": cap, "memory": "32Gi", "pods": 40})
                   .zone(f"z{i % zones}")
                   .label(HOSTNAME, f"n{i}").obj())
    return out


class TestSpread:
    def test_zone_do_not_schedule(self):
        nodes = _nodes(9, zones=3)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "a"})
                .obj() for i in range(12)]
        out = scan_vs_greedy(nodes, [], pods)
        assert (out >= 0).all()

    def test_zone_and_hostname(self):
        nodes = _nodes(8, zones=4)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "a"})
                .spread_constraint(2, HOSTNAME, "ScheduleAnyway", {"app": "a"})
                .obj() for i in range(16)]
        scan_vs_greedy(nodes, [], pods)

    def test_with_existing_pods(self):
        nodes = _nodes(6, zones=3)
        existing = [(make_pod(f"e{i}").req({"cpu": "2", "memory": "1Gi"})
                     .label("app", "a").obj(), f"n{i % 3}")
                    for i in range(5)]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "a"})
                .obj() for i in range(10)]
        scan_vs_greedy(nodes, existing, pods)


class TestInterPodAffinity:
    def test_self_anti_affinity(self):
        nodes = _nodes(8, zones=8)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .pod_affinity(ZONE, {"app": "a"}, anti=True)
                .obj() for i in range(10)]
        out = scan_vs_greedy(nodes, [], pods)
        # 8 zones → exactly 8 land, 2 fail
        assert int((out >= 0).sum()) == 8

    def test_required_affinity_escape_hatch(self):
        """First pod of a series allows itself (filtering.go:381-397)."""
        nodes = _nodes(6, zones=3)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .pod_affinity(ZONE, {"app": "a"})
                .obj() for i in range(6)]
        out = scan_vs_greedy(nodes, [], pods)
        assert (out >= 0).all()
        # all pods co-locate in ONE zone (affinity to self-series)
        zones = {int(out[i]) % 3 for i in range(6)}
        assert len(zones) == 1

    def test_preferred_affinity_scores(self):
        nodes = _nodes(6, zones=3)
        existing = [(make_pod("seed").req({"cpu": "1", "memory": "1Gi"})
                     .label("app", "a").obj(), "n2")]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .preferred_pod_affinity(ZONE, {"app": "a"}, weight=10)
                .obj() for i in range(4)]
        scan_vs_greedy(nodes, existing, pods)

    def test_anti_affinity_with_existing(self):
        nodes = _nodes(6, zones=3)
        existing = [(make_pod("e0").req({"cpu": "1", "memory": "1Gi"})
                     .label("app", "a")
                     .pod_affinity(ZONE, {"app": "a"}, anti=True)
                     .obj(), "n0")]
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "a")
                .pod_affinity(ZONE, {"app": "a"}, anti=True)
                .obj() for i in range(4)]
        out = scan_vs_greedy(nodes, existing, pods)
        # z0 vetoed by the existing pod: 2 remaining zones fit
        assert int((out >= 0).sum()) == 2


class TestFuzz:
    def test_randomized_clusters(self):
        rng = random.Random(7)
        for trial in range(12):
            n = rng.randint(4, 12)
            zones = rng.randint(2, 4)
            caps = [rng.choice([4, 8, 16]) for _ in range(n)]
            nodes = _nodes(n, zones=zones, seed_caps=caps)
            existing = []
            for i in range(rng.randint(0, 6)):
                existing.append((
                    make_pod(f"e{i}").req({"cpu": str(rng.randint(1, 3)),
                                           "memory": "1Gi"})
                    .label("app", rng.choice(["a", "b"])).obj(),
                    f"n{rng.randrange(n)}"))
            kind = rng.choice(["spread", "anti", "both"])
            w = make_pod("proto").req({"cpu": "1", "memory": "1Gi"}) \
                .label("app", "a")
            if kind in ("spread", "both"):
                w = w.spread_constraint(rng.choice([1, 2]), ZONE,
                                        rng.choice(["DoNotSchedule",
                                                    "ScheduleAnyway"]),
                                        {"app": "a"})
            if kind in ("anti", "both"):
                w = w.pod_affinity(HOSTNAME, {"app": "a"}, anti=True)
            proto = w.obj()
            pods = []
            for i in range(rng.randint(3, 14)):
                import copy
                p = copy.deepcopy(proto)
                p.metadata.name = f"p{trial}-{i}"
                p.metadata.uid = f"default/p{trial}-{i}"
                pods.append(p)
            scan_vs_greedy(nodes, existing, pods)


class TestMostAllocated:
    def test_most_allocated_spread_parity(self):
        """ISSUE 3 satellite: the greedy recomputes scores per step, so
        MostAllocated's non-monotone sequences (which bar the closed-form
        uniform path) stay exact — same-signature group runs under the
        bin-packing strategy skip the device scan too."""
        cfg = ScoreConfig(strategy="MostAllocated")
        nodes = _nodes(8, zones=4, cpu=32)
        pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
                .label("app", "m")
                .spread_constraint(2, ZONE, "DoNotSchedule", {"app": "m"})
                .obj() for i in range(16)]
        out = scan_vs_greedy(nodes, [], pods, cfg=cfg)
        assert (out >= 0).all()

    def test_most_allocated_engages_host_greedy(self):
        """The eligibility gate no longer rejects MostAllocated: with the
        wave path off, a same-signature group drain runs the greedy."""
        from kubernetes_tpu.backend.apiserver import APIServer
        from kubernetes_tpu.config import (KubeSchedulerConfiguration,
                                           KubeSchedulerProfile)
        from kubernetes_tpu.scheduler import Scheduler

        cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile(
            scoring_strategy="MostAllocated")])
        api = APIServer()
        sched = Scheduler(api, batch_size=64, config=cfg)
        sched.feature_gates.set("SpeculativeWavePlacement", False)
        for i in range(6):
            api.create_node(make_node(f"n{i}")
                            .capacity({"cpu": 32, "memory": "64Gi",
                                       "pods": 80})
                            .zone(f"z{i % 3}")
                            .label(HOSTNAME, f"n{i}").obj())
        for i in range(20):
            api.create_pod(make_pod(f"p{i}")
                           .req({"cpu": "500m", "memory": "512Mi"})
                           .label("app", "m")
                           .spread_constraint(3, ZONE, "DoNotSchedule",
                                              {"app": "m"}).obj())
        assert sched.schedule_pending() == 20
        assert sched.host_greedy_runs > 0


class TestSchedulerIntegration:
    def test_greedy_path_matches_scan_path_end_to_end(self):
        """Same workload through two Schedulers — host greedy on vs off —
        must produce identical binds."""
        def build(greedy_on):
            api = APIServer()
            sched = Scheduler(api, batch_size=64)
            if not greedy_on:
                sched._try_host_greedy = lambda *a, **k: None
            for i in range(9):
                api.create_node(make_node(f"n{i}").capacity(
                    {"cpu": 8, "memory": "16Gi", "pods": 20})
                    .zone(f"z{i % 3}").label(HOSTNAME, f"n{i}").obj())
            for i in range(24):
                api.create_pod(make_pod(f"p{i}")
                               .req({"cpu": "1", "memory": "1Gi"})
                               .label("app", "a")
                               .spread_constraint(1, ZONE, "DoNotSchedule",
                                                  {"app": "a"})
                               .obj())
            sched.schedule_pending()
            return {uid: p.spec.node_name for uid, p in api.pods.items()}

        assert build(True) == build(False)
