#!/usr/bin/env python
"""Headline benchmark: SchedulingBasic throughput + group-kernel cases.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N/270,
   "extra": {"TopologySpreading_...": {...}, "SchedulingPodAntiAffinity_...":
   {...}}}

`vs_baseline` divides by the reference's threshold for the same workload
(kubernetes/kubernetes test/integration/scheduler_perf configs):
  SchedulingBasic          ≥ 270  (misc/performance-config.yaml:67-75)
  TopologySpreading        ≥ 85   (topology_spreading/performance-config.yaml:20)
  SchedulingPodAntiAffinity ≥ 60  (affinity/performance-config.yaml:57-80)

Compile exclusion: each workload runs TWICE in this process — the first
(unmeasured) pass drives the scheduler through the exact same padded device
shapes (node bucket, batch bucket, uniform-run L/K/J variants, group
tensors), so every XLA executable the measured pass needs is already in the
in-process cache. The measured pass then re-runs the workload on a fresh
Scheduler/APIServer; a shape bucket compiled in pass one is a cache hit in
pass two regardless of the new Scheduler instance (the reported
warm_pass_s / measured_pass_s gap makes any residual compile visible).

Each measured run also appends its full Prometheus exposition to
`bench_metrics.prom` (the reference benchmark scrapes /metrics the same
way).

Env:
  KTPU_BENCH_SMALL=1   500-node / small-pod quick variants
  KTPU_BENCH_VERBOSE=1 per-batch progress on stderr
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CASES = [
    # (case, big workload, small workload, reference threshold)
    ("SchedulingBasic", "5000Nodes_10000Pods", "500Nodes_1000Pods", 270.0),
    ("TopologySpreading", "5000Nodes_5000Pods", "500Nodes", 85.0),
    ("SchedulingPodAntiAffinity", "5000Nodes_2000Pods", "500Nodes", 60.0),
]


def main() -> None:
    # raise gen0 thresholds so collection cycles don't land in the measured
    # window; the freeze happens after each warm pass, once the long-lived
    # survivors (interners, jit caches, compiled executables) exist
    import gc
    gc.set_threshold(100000, 50, 50)
    small = os.environ.get("KTPU_BENCH_SMALL") == "1"
    verbose = os.environ.get("KTPU_BENCH_VERBOSE") == "1"
    from kubernetes_tpu.perf.harness import run_config

    cfg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "kubernetes_tpu", "perf", "configs",
                       "performance-config.yaml")
    results = {}
    for case, big, small_wl, threshold in CASES:
        workload = small_wl if small else big
        t0 = time.perf_counter()
        run_config(cfg, case, workload)           # warm: compiles all shapes
        warm_s = time.perf_counter() - t0
        import gc
        gc.collect()
        gc.freeze()   # pin the warm pass's survivors out of future cycles
        t0 = time.perf_counter()
        got = run_config(cfg, case, workload, verbose=verbose,
                         metrics_path="bench_metrics.prom")
        measured_s = time.perf_counter() - t0
        if not got:
            raise SystemExit(f"workload {case}/{workload} not found")
        item, _ = got[0]
        results[f"{case}_{workload}"] = {
            "value": round(item.average, 1),
            "vs_baseline": round(item.average / threshold, 2),
            "p50": round(item.perc50), "p95": round(item.perc95),
            "p99": round(item.perc99), "pods": item.pods,
            "warm_pass_s": round(warm_s, 1),
            "measured_pass_s": round(measured_s, 1),
        }
        if verbose:
            print(f"  {case}/{workload}: {item.average:.1f} pods/s "
                  f"(warm pass {warm_s:.1f}s, measured {measured_s:.1f}s)",
                  file=sys.stderr)

    head_key = next(iter(results))
    head = results[head_key]
    print(json.dumps({
        "metric": f"{head_key}_throughput",
        "value": head["value"],
        "unit": "pods/s",
        "vs_baseline": head["vs_baseline"],
        "extra": {k: v for k, v in results.items() if k != head_key},
    }))


if __name__ == "__main__":
    main()
