#!/usr/bin/env python
"""Headline benchmark: SchedulingBasic throughput + group-kernel cases.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N/270,
   "summary": {"<Case>_<Workload>": {"pods_per_s": N, "p50": N, "p99": N,
               "attempt_p50_ms": N, "attempt_p99_ms": N,
               "e2e_p50_ms": N, "e2e_p99_ms": N}, ...},
   "extra": {"TopologySpreading_...": {...}, "SchedulingPodAntiAffinity_...":
   {...}}}

`summary` is the NORMALIZED per-workload block — every workload (headline
included) with its throughput and latency percentiles in one place, the
contract `tools/bench_compare.py` (the regression sentinel) reads; `extra`
keeps the full per-workload detail (passes, warm/measured seconds, drain
phase sums, wave stats, host_top_frames).

`vs_baseline` divides by the reference's threshold for the same workload
(kubernetes/kubernetes test/integration/scheduler_perf configs):
  SchedulingBasic          ≥ 270  (misc/performance-config.yaml:67-75)
  TopologySpreading        ≥ 85   (topology_spreading/performance-config.yaml:20)
  SchedulingPodAntiAffinity ≥ 60  (affinity/performance-config.yaml:57-80)

Compile exclusion: each workload first runs an UNMEASURED warm pass that
drives the scheduler through the exact same padded device shapes (node
bucket, batch bucket, uniform L/K/J variants, group tensors), so every XLA
executable the measured passes need is already in the in-process cache.
Then THREE measured passes run on fresh Scheduler/APIServer instances and
the MEDIAN is reported (the tunneled device's per-execution latency
jitters ±20% against sub-second windows); warm_pass_s records the
cold-start compile cost separately.

Each measured run also appends its full Prometheus exposition to
`bench_metrics.prom` (the reference benchmark scrapes /metrics the same
way).

Env:
  KTPU_BENCH_SMALL=1   500-node / small-pod quick variants
  KTPU_BENCH_VERBOSE=1 per-batch progress on stderr
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CASES = [
    # (case, big workload, small workload, reference threshold)
    ("SchedulingBasic", "5000Nodes_10000Pods", "500Nodes_1000Pods", 270.0),
    ("SchedulingNodeAffinity", "5000Nodes", "500Nodes", 220.0),
    ("TopologySpreading", "5000Nodes_5000Pods", "500Nodes", 85.0),
    ("SchedulingPodAntiAffinity", "5000Nodes_2000Pods", "500Nodes", 60.0),
    ("MixedSchedulingBasePod", "5000Nodes", "500Nodes", 140.0),
    # >4 interacting signatures per drain (ISSUE 8 / ROADMAP item 4): the
    # cliff the drain compiler removed, regression-guarded forever. The
    # reference threshold reuses TopologySpreading's floor (same
    # constraint family; no reference workload mixes signatures)
    ("MixedHighSignature", "5000Nodes", "500Nodes", 85.0),
    # no reference workload exists for preemption churn; vs_baseline uses
    # the SchedulingBasic floor (the stream being scheduled THROUGH the
    # pending nominations is plain pods)
    ("PreemptionChurn", "5000Nodes_10000Pods", "500Nodes", 270.0),
    # gang workload suite (ISSUE 7 / ROADMAP item 3): trace-driven LLM
    # training gangs solved as one all-or-nothing device dispatch each,
    # and co-located inference + training with gang-on-gang preemption.
    # No reference workloads exist; vs_baseline reuses the SchedulingBasic
    # floor (gang members are plain pods)
    ("GangTraining", "5000Nodes", "500Nodes", 270.0),
    ("CoLocatedInference", "5000Nodes", "500Nodes", 270.0),
]

# PreemptionChurn's preemptor wave is the createPods op at this template
# index (perf/configs/performance-config.yaml): its wall time is recorded
# separately as preemption_wave_s — the wave runs OUTSIDE the measured
# window. Per-workload regressions inside the window are the `summary`
# block's job (tools/bench_compare.py gates every workload, not just the
# headline); this extra keeps the out-of-window wave visible too.
PREEMPTION_WAVE_OP = "createPods[2]"


_SHARDED_CASE = r'''
import json, sys, time
sys.path.insert(0, REPO)
# accelerator site hooks may re-pin jax_platforms at interpreter start;
# the env var alone is not enough (same dance as tests/conftest.py)
import jax
jax.config.update("jax_platforms", "cpu")
from kubernetes_tpu.api.types import ObjectMeta, PodGroup, Workload
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.parallel.sharding import make_mesh
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

mesh = make_mesh(8)

def run():
    api = APIServer()
    sched = Scheduler(api, batch_size=BATCH, mesh=mesh)
    for i in range(NODES):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 16}").obj())
    sched.prime()
    # defer the one-shot lane profile: it re-dispatches the scan-shaped
    # program to decompose it, which belongs AFTER the throughput clock
    # stops, not inside the measured window
    sched.shard_profile_auto = False
    samples = [(time.perf_counter(), 0)]
    created = gidx = 0
    while created < PODS:
        take = min(CHUNK, PODS - created)
        if GANG:
            # all-or-nothing gangs of 8 (run_gang_sharded's device path)
            for _ in range(take // 8):
                wl = "gang-%d" % gidx; gidx += 1
                api.create_workload(Workload(
                    metadata=ObjectMeta(name=wl),
                    pod_groups=[PodGroup(name="workers", min_count=8)]))
                for _ in range(8):
                    api.create_pod(make_pod(f"pod-{created}").req(
                        {"cpu": "900m", "memory": "1Gi"})
                        .workload(wl).obj())
                    created += 1
        else:
            for i in range(take):
                api.create_pod(make_pod(f"pod-{created + i}").req(
                    {"cpu": "900m", "memory": "1Gi"}).obj())
            created += take
        sched.schedule_pending(wait=False)
        samples.append((time.perf_counter(), sched.scheduled_count))
    sched.schedule_pending()
    samples.append((time.perf_counter(), sched.scheduled_count))
    assert sched.scheduled_count == PODS, sched.scheduled_count
    dt = samples[-1][0] - samples[0][0]
    rates = []
    t0, c0 = samples[0]
    for t1, c1 in samples[1:]:
        if c1 > c0 and t1 > t0:
            rates.append((c1 - c0) / (t1 - t0))
            t0, c0 = t1, c1
    rates.sort()
    perc = lambda p: rates[min(len(rates) - 1, int(p * len(rates)))] \
        if rates else 0.0
    m = sched.metrics
    if sched.audit is not None:
        sched.audit.flush()
    from kubernetes_tpu.perf.critical_path import aggregate as cp_agg
    return {
        "pods_per_s": round(PODS / dt, 1), "seconds": round(dt, 3),
        "p50": round(perc(0.50)), "p99": round(perc(0.99)),
        "attempt_p50_ms": round(m.attempt_duration.quantile(0.50) * 1e3, 3),
        "attempt_p99_ms": round(m.attempt_duration.quantile(0.99) * 1e3, 3),
        "e2e_p50_ms": round(m.sli_duration.quantile(0.50) * 1e3, 3),
        "e2e_p99_ms": round(m.sli_duration.quantile(0.99) * 1e3, 3),
        "slo": sched.slo.snapshot(compact=True),
        # sharded-lane decomposition of this pass (ISSUE 16): per-lane
        # seconds, imbalance ratio and comms share — bench_compare's
        # sharded-lane regression gate reads this off the median pass
        "lanes": sched.profile_shard_lanes() or {},
        # per-drain bottleneck verdicts folded over this pass's flight
        # ring (ISSUE 20): the sharded tier's headroom scoreboard
        "critical_path": cp_agg(d.get("criticalPath")
                                for d in sched.flight.dump()),
    }

run()           # warm pass: compiles the node-axis-sharded program
passes = [run() for _ in range(RUNS)]
passes.sort(key=lambda d: d["pods_per_s"])
out = passes[len(passes) // 2]
out["passes"] = [d["pods_per_s"] for d in passes]
print(json.dumps(out))
'''


_STREAM_SHARDED_CASE = r'''
import json, sys, time
sys.path.insert(0, REPO)
import jax
jax.config.update("jax_platforms", "cpu")
from kubernetes_tpu.backend.apiserver import APIServer
from kubernetes_tpu.parallel.sharding import make_mesh
from kubernetes_tpu.pipeline import StreamingPipeline
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.testing.workloads import chunked, poisson_arrivals

mesh = make_mesh(8)

def run():
    api = APIServer()
    sched = Scheduler(api, batch_size=BATCH, mesh=mesh)
    for i in range(NODES):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 16}").obj())
    sched.prime()
    sched.shard_profile_auto = False
    # warm the sharded drain shapes before the paced window starts
    for i in range(WARM):
        api.create_pod(make_pod(f"warm-{i}").req(
            {"cpu": "900m", "memory": "1Gi"}).obj())
    sched.schedule_pending()
    chk = sched.metrics.sli_duration.merged_counts()
    pods = [make_pod(f"pod-{i}").req(
        {"cpu": "900m", "memory": "1Gi"}).obj() for i in range(PODS)]
    events = list(poisson_arrivals(chunked(pods, 128), qps=QPS, seed=0))
    pipe = StreamingPipeline(sched, latency_budget_s=0.005)
    pipe.start()
    t0 = time.perf_counter()
    for due, chunk in events:
        lag = t0 + due - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        pipe.feed(chunk)
    pipe.drain()
    dt = time.perf_counter() - t0
    pipe.stop()
    st = pipe.stats()
    m = sched.metrics
    assert sched.scheduled_count == WARM + PODS, sched.scheduled_count
    assert not st["errors"], st["errors"]
    from kubernetes_tpu.perf.critical_path import aggregate as cp_agg
    return {
        "pods_per_s": round(PODS / dt, 1), "seconds": round(dt, 3),
        "offered_qps": QPS,
        "e2e_p50_ms": round(
            m.sli_duration.quantile(0.50, since=chk) * 1e3, 3),
        "e2e_p99_ms": round(
            m.sli_duration.quantile(0.99, since=chk) * 1e3, 3),
        "pipeline": st,
        "critical_path": cp_agg(d.get("criticalPath")
                                for d in sched.flight.dump()),
    }

passes = [run() for _ in range(RUNS)]
passes.sort(key=lambda d: d["pods_per_s"])
out = passes[len(passes) // 2]
out["passes"] = [d["pods_per_s"] for d in passes]
print(json.dumps(out))
'''


def streaming_sharded_case(nodes: int, pods: int, qps: float, runs: int,
                           warm: int = 2048, batch: int = 2048,
                           timeout: int = 900) -> dict:
    """StreamingSharded (ISSUE 18): the open-loop Poisson arrival process
    feeding the streaming drain pipeline over the node-axis-SHARDED mesh
    backend — 8-virtual-device CPU mesh in a subprocess, same dance as
    sharded_case. Proves the ingest/device/commit overlap composes with
    XLA collectives over the node axis, and reports the same per-tier
    sustained pods/s + delta e2e percentiles as StreamingBasic."""
    import subprocess
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("REPO = %r\nNODES = %d\nPODS = %d\nQPS = %g\nRUNS = %d\n"
            "WARM = %d\nBATCH = %d\n"
            % (os.path.dirname(os.path.abspath(__file__)), nodes, pods,
               qps, runs, warm, batch)) + _STREAM_SHARDED_CASE
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0 or not out.stdout.strip():
            return {"error": f"probe exited {out.returncode}",
                    "stderr_tail": out.stderr.strip()[-400:]}
        data = json.loads(out.stdout.strip().splitlines()[-1])
        data["devices"] = 8
        data["backend"] = "cpu-virtual-mesh"
        data["value"] = data["pods_per_s"]
        data["pods"] = pods
        return data
    except Exception as e:  # probe failure must not sink the headline
        return {"error": str(e)[:200]}


def sharded_case(nodes: int, pods: int, runs: int, gang: bool = False,
                 chunk: int = 256, batch: int = 2048,
                 timeout: int = 900) -> dict:
    """Run a Sharded* workload on the 8-virtual-device CPU mesh in a
    subprocess (the real chip is single-device; the driver's MULTICHIP
    dryrun validates compilation the same way). Returns a full summary
    entry — ROADMAP item 1's scoreboard, recorded in the BENCH trail
    and gated by tools/bench_compare.py instead of folklore. `gang`
    feeds all-or-nothing gangs of 8 (run_gang_sharded) instead of plain
    pods; `chunk`/`batch` size the creation wave and the drain span —
    the 50k-node tier sets both to the full pod count so ONE drain
    carries 10^5 pods through the closed-form sharded uniform tier."""
    import subprocess
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("REPO = %r\nNODES = %d\nPODS = %d\nRUNS = %d\n"
            "GANG = %d\nCHUNK = %d\nBATCH = %d\n"
            % (os.path.dirname(os.path.abspath(__file__)), nodes, pods,
               runs, int(gang), chunk, batch)) + _SHARDED_CASE
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode != 0 or not out.stdout.strip():
            return {"error": f"probe exited {out.returncode}",
                    "stderr_tail": out.stderr.strip()[-400:]}
        line = out.stdout.strip().splitlines()[-1]
        data = json.loads(line)
        data["devices"] = 8
        data["backend"] = "cpu-virtual-mesh"
        data["value"] = data["pods_per_s"]
        data["pods"] = pods
        return data
    except Exception as e:  # probe failure must not sink the headline
        return {"error": str(e)[:200]}


def ha_failover_case(nodes: int) -> dict:
    """Warm takeover vs cold start on the same N-node store (ISSUE 12):
    a ledger-warmed hot spare's takeover (final tail drain + delta
    resync + promote, `ha/standby.py`) against a fresh scheduler paying
    the full construct + LIST + prime() it replaces. The acceptance bar
    is warm < cold; the entry lands in the bench extras (it reports
    seconds, not throughput, so it stays out of the `summary` block)."""
    import time as _t
    from kubernetes_tpu.backend.apiserver import APIServer, LEASE_NAME
    from kubernetes_tpu.ha.standby import StandbyScheduler
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    t = {"now": 0.0}
    clock = lambda: t["now"]                                  # noqa: E731
    api = APIServer()
    for i in range(nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 16}").obj())
    leader = Scheduler(api, clock=clock)
    if leader.audit is not None:
        leader.audit.sample_rate = 1.0   # every drain hits the ledger
    api.acquire_lease(LEASE_NAME, "bench-leader", clock())
    leader.prime()
    for i in range(256):
        api.create_pod(make_pod(f"ha-pod-{i}").req(
            {"cpu": "900m", "memory": "1Gi"}).obj())
    leader.schedule_pending()
    if leader.audit is not None:
        leader.audit.flush()
    ledger = leader.audit.ledger if leader.audit is not None else None
    standby = StandbyScheduler(api, identity="bench-standby",
                               ledger=ledger, clock=clock)
    standby.tick()          # leader still holds: stays standby
    standby.sync()          # warm the spare: cache + arrays + JIT
    t["now"] += 20.0        # leader dies (stops renewing past expiry)
    standby.tick()          # wins the lease; takeover() runs inside
    warm_s = standby.failover_s
    t0 = _t.perf_counter()
    cold = Scheduler(api)
    cold.prime()
    cold_s = _t.perf_counter() - t0
    return {
        "value": round(warm_s * 1e3, 2), "unit": "ms",
        "warm_failover_s": round(warm_s, 4),
        "cold_start_s": round(cold_s, 4),
        "warm_beats_cold": warm_s < cold_s,
        "speedup": round(cold_s / warm_s, 2) if warm_s else 0.0,
        "nodes": nodes, "ledger_drains_seen": standby.drains_seen,
    }


def _env_fingerprint() -> dict:
    """Execution-environment stamp for the payload: cpu model/count,
    interpreter + array-stack versions, JAX platform. bench_compare
    reads both sides' stamps and downgrades cross-container THROUGHPUT
    failures to warnings on mismatch — numbers from different silicon
    are not an A/B — while same-container comparisons stay strict."""
    import platform
    cpu_model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    cpu_model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    versions = {"python": platform.python_version()}
    for mod in ("jax", "numpy"):
        try:
            versions[mod] = __import__(mod).__version__
        except Exception:
            versions[mod] = ""
    # accelerator identity (ISSUE 20 satellite): resolved backend +
    # device kind/count, not just the requested JAX_PLATFORMS — numbers
    # from a different accelerator are not an A/B even when the env var
    # matches, and bench_compare's mismatch downgrade keys on this too
    accel = {"backend": "", "device_kind": "", "device_count": 0}
    try:
        import jax
        devs = jax.devices()
        accel = {
            "backend": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else "",
            "device_count": len(devs),
        }
    except Exception:
        pass
    return {
        "cpu_model": cpu_model,
        "cpu_count": os.cpu_count() or 0,
        "versions": versions,
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "accelerator": accel,
    }


def multi_shard_case(nodes: int, pods: int) -> dict:
    """Sharded control plane (ISSUE 17): N=4 fenced scheduler instances
    over ONE cluster, each draining its namespace slice under its own
    shard lease, with a forced mid-run steal. Reports AGGREGATE pods/s
    (it lands in the summary block) plus the handoff latency extras and
    the `shard` proof block — zero double-binds, zero shadow-oracle
    divergence — that tools/bench_compare.py gates under --slo."""
    import time as _t
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.ha.shards import ShardManager, ShardScheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    n_shards = 4
    t = {"now": 0.0}
    clock = lambda: t["now"]                                  # noqa: E731
    api = APIServer()
    for i in range(nodes):
        api.create_node(make_node(f"n{i}").capacity(
            {"cpu": 32, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 16}").obj())
    insts = []
    for i in range(n_shards):
        inst = ShardScheduler(api, identity=f"bench-shard-{i}",
                              clock=clock, batch_size=256)
        if inst.scheduler.audit is not None:
            inst.scheduler.audit.sample_rate = 1.0
        inst.scheduler.dispatcher.sleep = lambda _s: None
        insts.append(inst)
    mgr = ShardManager(api, instances=insts, clock=clock)
    mgr.wire_ledgers()
    mgr.split(n_shards, owners={i: insts[i] for i in range(n_shards)},
              assignments={f"default-scheduler/ns-{i}": i
                           for i in range(n_shards)})
    for i in range(pods):
        api.create_pod(make_pod(f"ms-pod-{i}", namespace=f"ns-{i % n_shards}")
                       .req({"cpu": "900m", "memory": "1Gi"}).obj())

    rebalance_dts = []
    t0 = _t.perf_counter()
    for round_no in range(200):
        for inst in insts:
            inst.tick()
            inst.scheduler.schedule_pending()
            # advance the simulated clock just enough to expire bind
            # backoffs: a 5s step would put every pod's queue→bind SLI
            # past the 5s e2e objective and the federated SLO block
            # below would report a driver artifact, not the fleet
            t["now"] += 0.05
            inst.scheduler.flush_queues()
        bound = sum(1 for p in api.pods.values() if p.spec.node_name)
        if round_no == 0 and bound < pods:
            # mid-run handoff: shard 3's slice steals over to instance 0
            rebalance_dts.append(mgr.steal(3, insts[0]))
        if bound >= pods:
            break
    wall_s = _t.perf_counter() - t0

    bound = sum(1 for p in api.pods.values() if p.spec.node_name)
    divergence = 0
    for inst in insts:
        if inst.scheduler.audit is not None:
            inst.scheduler.audit.flush()
        m = inst.scheduler.metrics
        divergence += sum(int(m.oracle_divergence.value(kind))
                          for kind in ("assignment", "reason", "verdict"))
    rebalance_dts.sort()
    # fleet observatory proof (ISSUE 19): every bound pod must stitch to
    # exactly ONE cross-shard timeline ending in bind_confirm (zero
    # orphaned per-instance fragments survive the mid-run steal), and
    # the fleet burns ONE federated SLO budget per SLI — the block
    # bench_compare --slo gates, replacing N private per-instance ones
    bound_uids = [p.uid for p in api.pods.values() if p.spec.node_name]
    coverage = mgr.stitcher.coverage(bound_uids)
    fed = mgr.fleet.federated_slo()
    return {
        "value": round(bound / wall_s, 1) if wall_s else 0.0,
        "pods": bound, "nodes": nodes, "shards": n_shards,
        "steals": mgr.steals,
        "rebalance_p50_ms": round(
            rebalance_dts[len(rebalance_dts) // 2] * 1e3, 2)
        if rebalance_dts else 0.0,
        "rebalance_max_ms": round(rebalance_dts[-1] * 1e3, 2)
        if rebalance_dts else 0.0,
        "cross_shard_conflicts": sum(i.conflicts for i in insts),
        # the chaos-matrix proof, bench-shaped: bench_compare --slo
        # fails on ANY double-bind or shadow-oracle divergence
        "shard": {
            "double_binds": api.binding_count - bound,
            "divergence": divergence,
            "ledgers_verified": all(
                i.audit_ledger() is not None
                and i.audit_ledger().verify()
                and i.audit_ledger().verify_handoffs() for i in insts),
            "journeys_total": coverage["pods"],
            "journeys_stitched": coverage["stitched"],
            "orphaned_fragments": coverage["orphaned"],
        },
        # ONE federated burn per SLI over the fleet (standbys excluded):
        # what --slo gates instead of N per-instance budgets
        "slo": {
            "breaches": fed.breaches(),
            "divergence_total": divergence,
            "federated": True,
            "shards": n_shards,
        },
    }


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace-dir", default="",
                    help="write one Chrome-trace JSON per workload "
                         "(spans of the median-candidate measured passes; "
                         "load at chrome://tracing or ui.perfetto.dev)")
    ap.add_argument("--profile-dir", default="",
                    help="write one collapsed-stack host profile per "
                         "workload (continuous profiler; render with "
                         "flamegraph.pl or speedscope.app)")
    ap.add_argument("--timeline-dir", default="",
                    help="write one JSON-lines telemetry timeline per "
                         "workload (obs/timeline.py per-second "
                         "aggregates: binds, requeue causes, e2e "
                         "segments, cluster-probe samples)")
    ap.add_argument("--cases", default="",
                    help="comma-separated case filter (e.g. "
                         "SchedulingBasic,TopologySpreading); default all")
    args = ap.parse_args()
    # raise gen0 thresholds so collection cycles don't land in the measured
    # window; the freeze happens after each warm pass, once the long-lived
    # survivors (interners, jit caches, compiled executables) exist
    import gc
    gc.set_threshold(100000, 50, 50)
    small = os.environ.get("KTPU_BENCH_SMALL") == "1"
    verbose = os.environ.get("KTPU_BENCH_VERBOSE") == "1"
    from kubernetes_tpu.perf.harness import run_config

    cfg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "kubernetes_tpu", "perf", "configs",
                       "performance-config.yaml")
    case_filter = {c for c in args.cases.split(",") if c}
    results = {}
    for case, big, small_wl, threshold in CASES:
        if case_filter and case not in case_filter:
            continue
        workload = small_wl if small else big
        t0 = time.perf_counter()
        run_config(cfg, case, workload)           # warm: compiles all shapes
        warm_s = time.perf_counter() - t0
        import gc
        gc.collect()
        gc.freeze()   # pin the warm pass's survivors out of future cycles
        # the measured window is sub-second while the tunneled device's
        # per-execution latency jitters ±20%: report the MEDIAN of 3
        # measured passes (each a full fresh-scheduler run) so the
        # headline reflects the configuration, not one draw of the tunnel
        passes = []
        measured_s = 0.0
        for _ in range(1 if small else 3):
            t0 = time.perf_counter()
            got = run_config(cfg, case, workload, verbose=verbose,
                             metrics_path="bench_metrics.prom",
                             trace_dir=args.trace_dir,
                             profile_dir=args.profile_dir,
                             timeline_dir=args.timeline_dir)
            measured_s += time.perf_counter() - t0
            if not got:
                raise SystemExit(f"workload {case}/{workload} not found")
            passes.append(got[0][0])
        passes.sort(key=lambda it: it.average)
        item = passes[len(passes) // 2]
        # per-phase drain breakdown + wave-placement stats of the median
        # pass (scheduler metrics; harness DataItem.extras)
        entry_extra = dict(item.extras)
        if case == "PreemptionChurn":
            waves = sorted(dict(it.op_seconds).get(PREEMPTION_WAVE_OP, 0.0)
                           for it in passes)
            entry_extra["preemption_wave_s"] = round(
                waves[len(waves) // 2], 2)
        results[f"{case}_{workload}"] = entry_extra | {
            "value": round(item.average, 1),
            "vs_baseline": round(item.average / threshold, 2),
            "p50": round(item.perc50), "p95": round(item.perc95),
            "p99": round(item.perc99), "samples": item.samples,
            "pods": item.pods,
            "passes": [round(it.average, 1) for it in passes],
            "warm_pass_s": round(warm_s, 1),      # cold-start incl. compiles
            "measured_pass_s": round(measured_s, 1),
        }
        if verbose:
            print(f"  {case}/{workload}: {item.average:.1f} pods/s "
                  f"(warm pass {warm_s:.1f}s, measured {measured_s:.1f}s)",
                  file=sys.stderr)

    if not case_filter or "StreamingBasic" in case_filter:
        # streaming drain pipeline under open-loop Poisson load (ISSUE
        # 18): each QPS tier runs BOTH the pipeline and the lock-step
        # phase-train twin at the SAME offered load — the A/B the
        # acceptance gate reads. Sustained pods/s is the open-loop
        # absorption rate; e2e percentiles are per-tier DELTAS over the
        # paced window (the warmup phase can't pollute them).
        tiers = (["500Nodes_10kQPS"] if small else
                 ["5000Nodes_10kQPS", "5000Nodes_20kQPS",
                  "5000Nodes_40kQPS"])
        for tier in tiers:
            for mode in ("pipeline", "lockstep"):
                wl_name = f"{tier}_{mode}"
                t0 = time.perf_counter()
                run_config(cfg, "StreamingBasic", wl_name)   # warm pass
                warm_s = time.perf_counter() - t0
                gc.collect()
                gc.freeze()
                passes = []
                for _ in range(1 if small else 3):
                    got = run_config(cfg, "StreamingBasic", wl_name,
                                     verbose=verbose,
                                     metrics_path="bench_metrics.prom")
                    if not got:
                        raise SystemExit(
                            f"workload StreamingBasic/{wl_name} not found")
                    passes.append(got[0][0])
                passes.sort(key=lambda it: it.average)
                item = passes[len(passes) // 2]
                entry = dict(item.extras)
                stream = entry.get("pipeline", {})
                entry.update({
                    "value": round(item.average, 1),
                    "vs_baseline": round(item.average / 270.0, 2),
                    "p50": round(item.perc50), "p95": round(item.perc95),
                    "p99": round(item.perc99), "samples": item.samples,
                    "pods": item.pods,
                    "passes": [round(it.average, 1) for it in passes],
                    "warm_pass_s": round(warm_s, 1),
                    # per-tier e2e = the paced window's delta quantiles
                    "e2e_p50_ms": stream.get("stream_e2e_p50_ms",
                                             entry.get("e2e_p50_ms", 0.0)),
                    "e2e_p99_ms": stream.get("stream_e2e_p99_ms",
                                             entry.get("e2e_p99_ms", 0.0)),
                })
                results[f"StreamingBasic_{wl_name}"] = entry
                if verbose:
                    print(f"  StreamingBasic/{wl_name}: "
                          f"{item.average:.1f} pods/s "
                          f"occ={stream.get('occupancy')}",
                          file=sys.stderr)

    if not case_filter or "StreamingSharded" in case_filter:
        # the streaming pipeline over the node-axis-sharded mesh backend
        nodes, pods, qps, runs = ((500, 1024, 5000, 1) if small
                                  else (5000, 8192, 20000, 2))
        entry = streaming_sharded_case(nodes, pods, qps, runs)
        if "error" not in entry:
            results[f"StreamingSharded_{nodes}Nodes"] = entry
        else:
            results[f"StreamingSharded_{nodes}Nodes_FAILED"] = entry

    if not case_filter or "ShardedBasic" in case_filter:
        # ShardedBasic (ISSUE 10 satellite / ROADMAP item 1): the
        # node-axis-sharded program's throughput as a first-class,
        # sentinel-gated workload — 8-virtual-device CPU mesh in a
        # subprocess (XLA's device-count flag must precede jax import)
        nodes, pods, runs = (500, 1024, 1) if small else (5000, 4096, 3)
        entry = sharded_case(nodes, pods, runs)
        if "error" not in entry:
            results[f"ShardedBasic_{nodes}Nodes"] = entry
        else:
            results[f"ShardedBasic_{nodes}Nodes_FAILED"] = entry

    if not case_filter or "ShardedGang" in case_filter:
        # ShardedGang (ISSUE 16): all-or-nothing gangs dispatched
        # through run_gang_sharded — the gang toolchain's mesh port,
        # bench-gated like every other sharded kernel
        nodes, pods, runs = (500, 512, 1) if small else (5000, 2048, 2)
        entry = sharded_case(nodes, pods, runs, gang=True)
        if "error" not in entry:
            results[f"ShardedGang_{nodes}Nodes"] = entry
        else:
            results[f"ShardedGang_{nodes}Nodes_FAILED"] = entry

    if (not small and not case_filter) or "Sharded50k" in case_filter:
        # the 50k-node tier (ISSUE 16): 10^5 pods through ONE drain of
        # the closed-form sharded uniform tier at 50k nodes — the scale
        # the paper's ≥50k pods/s target assumes, previously untouched
        # by the suite. One measured pass: the tier exists to prove the
        # shape compiles and completes, percentile noise is the 5k
        # cases' job
        entry = sharded_case(50000, 100000, 1, chunk=100000,
                             batch=100000, timeout=3000)
        if "error" not in entry:
            results["ShardedBasic_50000Nodes"] = entry
        else:
            results["ShardedBasic_50000Nodes_FAILED"] = entry

    if not case_filter or "HAFailover" in case_filter:
        # warm-spare takeover vs cold start (ISSUE 12 / ROADMAP item 5):
        # recorded in the extras, not the summary — it reports seconds
        nodes = 500 if small else 5000
        try:
            results[f"HAFailover_{nodes}Nodes"] = ha_failover_case(nodes)
        except Exception as e:   # HA probe must not sink the headline
            results[f"HAFailover_{nodes}Nodes_FAILED"] = {
                "error": str(e)[:200]}

    if not case_filter or "MultiShardBasic" in case_filter:
        # the sharded control plane (ISSUE 17 / ROADMAP item 4): 4
        # fenced instances over one cluster with a mid-run steal; lands
        # in the summary (aggregate pods/s) and carries the `shard`
        # zero-double-bind/zero-divergence block for the --slo gate
        nodes, pods = (500, 512) if small else (5000, 4096)
        try:
            results[f"MultiShardBasic_{nodes}Nodes"] = \
                multi_shard_case(nodes, pods)
        except Exception as e:   # the probe must not sink the headline
            results[f"MultiShardBasic_{nodes}Nodes_FAILED"] = {
                "error": str(e)[:200]}

    if not results:
        raise SystemExit(f"--cases {args.cases!r} matched no case")

    # normalized per-workload summary (the bench_compare.py contract):
    # every workload's throughput + latency percentiles in ONE block, so
    # neither the sentinel nor a human parses `extra` ad hoc — fixing the
    # headline blindness where phases outside the headline metric (and
    # every non-headline workload) had no first-class number
    from kubernetes_tpu.perf.critical_path import phase_shares
    summary = {}
    for key, entry in results.items():
        if "error" in entry or entry.get("unit") in ("s", "ms"):
            continue    # HAFailover reports time, not throughput
        # ONE share implementation (ISSUE 20 bugfix): the same
        # perf/critical_path.phase_shares the pipeline occupancy block
        # uses — bench and pipeline can no longer drift apart on what
        # "host share" means over the same FlightRecorder window
        shares = phase_shares({
            "host_build": float(entry.get("host_build_s", 0.0)),
            "device": float(entry.get("device_s", 0.0)),
            "commit": float(entry.get("commit_s", 0.0)),
        })
        # critical-path headroom (ISSUE 20): verdict histogram + the
        # projected ceiling if the window's dominant cause were free
        cp = dict(entry.get("critical_path", {}))
        if cp.get("ceiling_factor"):
            cp["ceiling_pods_per_s"] = round(
                float(entry["value"]) * float(cp["ceiling_factor"]), 1)
        summary[key] = {
            "pods_per_s": entry["value"],
            "p50": entry.get("p50", 0), "p99": entry.get("p99", 0),
            "attempt_p50_ms": entry.get("attempt_p50_ms", 0.0),
            "attempt_p99_ms": entry.get("attempt_p99_ms", 0.0),
            # queue→bind e2e percentiles (ISSUE 13): the SLI clock that
            # starts at FIRST enqueue and survives requeues — what
            # tools/bench_compare.py's e2e-latency gate reads.
            "e2e_p50_ms": entry.get("e2e_p50_ms", 0.0),
            "e2e_p99_ms": entry.get("e2e_p99_ms", 0.0),
            # host-phase shares of the drain cycle (ISSUE 9): what
            # fraction of scheduler_drain_phase_seconds Python still owns.
            # host_share = (host_build + commit) / cycle is the columnar
            # ingest engine's regression contract — tools/bench_compare.py
            # gates a >10% relative regression of it per workload.
            "phase_pct": {
                phase: round(100.0 * frac, 1)
                for phase, frac in shares["shares"].items()
            },
            "host_share": shares["host_share"],
            # SLO engine verdict at bench end (obs/slo.py): burn-rate
            # breaches + audit divergence count — what bench_compare's
            # --slo gate reads (fail on breach or nonzero divergence)
            "slo": entry.get("slo", {}),
            # per-kernel device-time breakdown (ISSUE 14, kernel
            # observatory delta over the median pass): seconds + p50/p99
            # per JIT entry — what bench_compare's per-kernel p99 gate
            # reads, and the named decomposition of device_s above
            "kernels": entry.get("kernels", {}),
            # sharded-lane profile of the median pass (ISSUE 16): comms
            # share + imbalance ratio, the decomposition bench_compare's
            # sharded-lane gate regresses on ({} for unsharded cases)
            "lanes": entry.get("lanes", {}),
            # sharded-control-plane proof block (ISSUE 17): double-bind
            # and divergence counts bench_compare's --slo gate fails on
            # ({} for single-instance cases)
            "shard": entry.get("shard", {}),
            # streaming-pipeline occupancy block (ISSUE 18): per-stage
            # busy seconds, overlap factor (busySum/wall), backpressure
            # and batch-close counts ({} for non-streaming cases)
            "pipeline": entry.get("pipeline", {}),
            # critical-path headroom block (ISSUE 20): per-drain verdict
            # histogram, per-cause seconds, the window's dominant cause
            # and the projected pods/s ceiling — what bench_compare's
            # --attribute mode diffs to EXPLAIN a throughput delta
            "critical_path": cp,
        }

    head_key = next(iter(results))
    head = results[head_key]
    print(json.dumps({
        "metric": f"{head_key}_throughput",
        "value": head["value"],
        "unit": head.get("unit", "pods/s"),
        "vs_baseline": head.get("vs_baseline", 0.0),
        # environment fingerprint (ISSUE 19): lets bench_compare tell a
        # cross-container comparison from a same-container A/B
        "env": _env_fingerprint(),
        "summary": summary,
        "extra": {k: v for k, v in results.items() if k != head_key},
    }))


if __name__ == "__main__":
    main()
