#!/usr/bin/env python
"""Headline benchmark: SchedulingBasic 5000Nodes_10000Pods throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N/270}

vs_baseline divides by the reference's threshold for the same workload
(kubernetes/kubernetes test/integration/scheduler_perf/misc/
performance-config.yaml:67-75, minimum average 270 pods/s).

Compile time is excluded: a warm-up workload with identical padded device
shapes (node bucket 8192, pod batch 512) runs first; the measured phase then
reuses the jitted program.

Env:
  KTPU_BENCH_SMALL=1   500 nodes / 1000 pods quick run
  KTPU_BENCH_VERBOSE=1 per-batch progress on stderr
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:67-75 threshold


def main() -> None:
    small = os.environ.get("KTPU_BENCH_SMALL") == "1"
    verbose = os.environ.get("KTPU_BENCH_VERBOSE") == "1"
    from kubernetes_tpu.perf.harness import run_config

    cfg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "kubernetes_tpu", "perf", "configs",
                       "performance-config.yaml")
    workload = "500Nodes_1000Pods" if small else "5000Nodes_10000Pods"

    # warm-up: same device shape buckets (8192-node rows only arise in the
    # big run; the small warmup still compiles the 512-wide batch program
    # for its own bucket). Use a miniature run of the same case.
    if not small:
        run_config(cfg, "SchedulingBasic", "500Nodes_1000Pods")
    else:
        run_config(cfg, "SchedulingBasic", "50Nodes_100Pods")

    results = run_config(cfg, "SchedulingBasic", workload, verbose=verbose)
    if not results:
        raise SystemExit(f"workload {workload} not found")
    item, _threshold = results[0]
    print(json.dumps({
        "metric": f"SchedulingBasic_{workload}_throughput",
        "value": round(item.average, 1),
        "unit": "pods/s",
        "vs_baseline": round(item.average / BASELINE_PODS_PER_SEC, 2),
    }))
    if verbose:
        print(f"  pods={item.pods} duration={item.duration_s:.2f}s "
              f"p50={item.perc50:.0f} p95={item.perc95:.0f} p99={item.perc99:.0f}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
